#!/usr/bin/env python
"""Probe the packed train step's sparse tail, honestly (value-synced).

Round-4 question (VERDICT r3 #1): the packed step spends ~6-7 sparse
M-row ops per step (fwd gather, argsort, perm gather, segment-sum, 2 RMW
gathers, 2 scatters).  Which of them actually cost, and does the
candidate redesign — ONE wide scatter-add into a dense [VP, 128] grad
buffer followed by a DENSE Adagrad sweep (zero-grad identity makes the
sweep exact) — beat the sort+segsum+RMW pipeline, and at which vocab
does the O(V) dense sweep stop paying?

Everything here times marginal fori_loop slopes or interleaved A/B
windows closed by a VALUE fetch (bench.forced_sync rationale, DESIGN §6
round-3 correction).  Prints one JSON dict.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=3000, what="probe_packed.py")

import jax
import jax.numpy as jnp
import numpy as np

from bench import forced_sync, make_batch, zipf_ids
from fast_tffm_tpu.models import FMModel
from fast_tffm_tpu.optim import AdagradState
from fast_tffm_tpu.ops.packed_table import (
    LANES,
    packed_gather,
    packed_rows,
    rows_per_tile,
)
from fast_tffm_tpu.trainer import (
    TrainState,
    init_packed_state,
    make_packed_train_step,
    packed_train_step_body,
)

BATCH = 16384
NNZ = 39
K = 8
D = 1 + K


# --- candidate: dense-G packed step --------------------------------------


def lane_spread(g, slot, p, d):
    """[M, D] per-occurrence grads -> [M, 128] with each row's grad in its
    slot lanes — ONE broadcast pass (one_hot [M,p] outer g) instead of p
    masked-slice passes over [M,128]."""
    m = g.shape[0]
    oh = jax.nn.one_hot(slot, p, dtype=g.dtype)  # [M, p]
    g128 = (oh[:, :, None] * g[:, None, :]).reshape(m, p * d)
    if p * d < LANES:
        g128 = jnp.pad(g128, ((0, 0), (0, LANES - p * d)))
    return g128


def dense_g_step_body(model, lr, state: TrainState, batch):
    """packed_train_step_body with the sparse tail replaced by:
    scatter-ADD g128 into a dense [VP, 128] zero buffer, then a dense
    elementwise Adagrad sweep.  Untouched elements see G == 0, the
    Adagrad identity, so the sweep is exact."""
    from fast_tffm_tpu.models.base import Batch
    from fast_tffm_tpu.trainer import batch_loss

    d = model.row_dim
    p = rows_per_tile(d)
    rows = packed_gather(state.table, batch.ids, d)
    grad_fn = jax.value_and_grad(
        partial(batch_loss, model), argnums=(0, 1), has_aux=True
    )
    (_, data_loss), (g_rows, g_dense) = grad_fn(rows, state.dense, batch)

    flat_ids = batch.ids.reshape(-1)
    m = flat_ids.shape[0]
    g = g_rows.reshape(m, d)
    slot = (flat_ids % p).astype(jnp.int32)
    phys = (flat_ids // p).astype(jnp.int32)
    g128 = lane_spread(g, slot, p, d)
    G = jnp.zeros_like(state.table).at[phys].add(g128, mode="drop")
    acc2 = state.table_opt.accum + G * G
    table = state.table - lr * G / jnp.sqrt(acc2)
    return (
        TrainState(table, AdagradState(acc2), state.dense, state.dense_opt,
                   state.step + 1),
        data_loss,
    )


def make_dense_g_step(model, lr):
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return dense_g_step_body(model, lr, state, batch)

    return step


# --- interleaved A/B of full steps ---------------------------------------


def ab_steps(variants, batches, iters=10, windows=5):
    """variants: {name: (step, state)}.  Interleave one window per variant
    per round; value-sync closes every window.  Returns per-variant window
    rates (ex/s)."""
    out = {name: [] for name in variants}
    states = {}
    for name, (step, state) in variants.items():
        state, _ = step(state, batches[0])  # compile
        forced_sync(state)
        for i in range(1, 3):
            state, _ = step(state, batches[i % len(batches)])
        forced_sync(state)
        states[name] = state
    for _ in range(windows):
        for name, (step, _) in variants.items():
            state = states[name]
            t0 = time.perf_counter()
            for i in range(iters):
                state, _ = step(state, batches[i % len(batches)])
            forced_sync(state)
            dt = time.perf_counter() - t0
            states[name] = state
            out[name].append(BATCH * iters / dt)
    return out


# --- per-op fori_loop slopes ----------------------------------------------


def slope_ms(fn, arrays, k_lo=4, k_hi=16, reps=3):
    """Marginal ms per op application: fn(arrays, k) runs the op k times
    inside one jit (carry-chained); cost = (t_hi - t_lo)/(k_hi - k_lo),
    best of reps (contention only slows)."""
    jfn = jax.jit(fn, static_argnums=(1,))
    for k in (k_lo, k_hi):  # compile both
        float(jfn(arrays, k))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jfn(arrays, k_lo))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(jfn(arrays, k_hi))
        t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (k_hi - k_lo))
    return best * 1e3


def main():
    vocab = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 24
    rng = np.random.default_rng(0)
    res = {"vocab": vocab, "batch": BATCH, "nnz": NNZ, "d": D}
    import atexit

    atexit.register(lambda: print(json.dumps(res), flush=True))
    p = rows_per_tile(D)
    vp = packed_rows(vocab, D)
    m = BATCH * NNZ
    res["p"] = p
    res["vp"] = vp
    res["m"] = m

    model = FMModel(vocabulary_size=vocab, factor_num=K, order=2)
    batches = [make_batch(zipf_ids(rng, (BATCH, NNZ), vocab), i) for i in range(8)]

    # --- full-step A/B: current packed vs dense-G ---
    cur = make_packed_train_step(model, 0.01)
    dng = make_dense_g_step(model, 0.01)
    s_cur = init_packed_state(model, jax.random.key(0))
    s_dng = init_packed_state(model, jax.random.key(0))
    ab = ab_steps({"packed_current": (cur, s_cur), "dense_g": (dng, s_dng)}, batches)
    for name, rates in ab.items():
        res[f"{name}_exs_windows"] = [round(r, 1) for r in rates]
        res[f"{name}_exs_median"] = round(float(np.median(rates)), 1)
        res[f"{name}_step_ms_median"] = round(BATCH / np.median(rates) * 1e3, 2)
    del s_cur, s_dng, cur, dng

    # --- numerical agreement spot check (tiny vocab, CPU-free) ---
    tm = FMModel(vocabulary_size=1 << 12, factor_num=K, order=2)
    tb = make_batch(zipf_ids(rng, (256, NNZ), 1 << 12), 99)
    sa = init_packed_state(tm, jax.random.key(1))
    sb = init_packed_state(tm, jax.random.key(1))
    sa, la = make_packed_train_step(tm, 0.01)(sa, tb)
    sb, lb = make_dense_g_step(tm, 0.01)(sb, tb)
    res["parity_max_abs_table_diff"] = float(
        jnp.max(jnp.abs(sa.table - sb.table))
    )
    res["parity_loss_diff"] = float(jnp.abs(la - lb))
    del sa, sb

    # --- per-op slopes at the probe shapes ---
    ids = jnp.asarray(zipf_ids(rng, (m,), vocab))
    phys = (ids // p).astype(jnp.int32)
    packed = jnp.zeros((vp, LANES), jnp.float32) + 0.01
    g128 = jnp.asarray(rng.normal(size=(m, LANES)).astype(np.float32))

    def chain_gather(arrays, k):
        pk, ph = arrays

        def body(i, s):
            ph2 = jnp.minimum(ph + (jnp.int32(s) & 1), pk.shape[0] - 1)
            return jnp.float32(jnp.sum(pk[ph2][:, :2]) * 1e-9) + s * 0.5

        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    res["op_wide_gather_big_ms"] = round(slope_ms(chain_gather, (packed, phys)), 3)

    def chain_scatter_add(arrays, k):
        pk, ph, g = arrays

        def body(i, s):
            ph2 = jnp.minimum(ph + (jnp.int32(s) & 1), pk.shape[0] - 1)
            G = jnp.zeros_like(pk).at[ph2].add(g, mode="drop")
            return jnp.float32(jnp.sum(G[:2]) * 1e-9) + s * 0.5

        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    res["op_wide_scatter_add_big_ms"] = round(
        slope_ms(chain_scatter_add, (packed, phys, g128)), 3
    )

    def chain_sort(arrays, k):
        (idv,) = arrays

        def body(i, s):
            srt = jnp.sort(idv ^ (jnp.int32(s) & 1))
            return jnp.float32(srt[0] + srt[-1]) * 1e-9 + s * 0.5

        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    res["op_argsort_ms"] = round(slope_ms(chain_sort, (ids,)), 3)

    def chain_perm_gather(arrays, k):
        g, ph = arrays
        order = jnp.argsort(ph)

        def body(i, s):
            o2 = jnp.minimum(order + (jnp.int32(s) & 1), g.shape[0] - 1)
            return jnp.float32(jnp.sum(g[o2][:, :2]) * 1e-9) + s * 0.5

        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    res["op_perm_gather_ms"] = round(slope_ms(chain_perm_gather, (g128, phys)), 3)

    def chain_segsum(arrays, k):
        g, ph = arrays
        sp = jnp.sort(ph)
        is_new = jnp.concatenate([jnp.ones((1,), bool), sp[1:] != sp[:-1]])
        seg = jnp.cumsum(is_new) - 1

        def body(i, s):
            g2 = g * (1.0 + 0.0 * s)
            ss = jax.ops.segment_sum(g2, seg, num_segments=g.shape[0])
            return jnp.float32(jnp.sum(ss[:2]) * 1e-9) + s * 0.5

        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    res["op_segment_sum_ms"] = round(slope_ms(chain_segsum, (g128, phys)), 3)

    def chain_dense_sweep(arrays, k):
        pk, g = arrays
        acc0 = pk + 0.1

        def body(i, carry):
            t, a = carry
            G = g * (1.0 + 0 * t[0, 0])
            a2 = a + G * G
            t2 = t - 0.01 * G / jnp.sqrt(a2)
            return (t2, a2)

        t2, a2 = jax.lax.fori_loop(0, k, body, (pk, acc0))
        return jnp.float32(t2[0, 0] + a2[-1, -1])

    gdense = jnp.zeros((vp, LANES), jnp.float32) + 1e-4
    res["op_dense_sweep_ms"] = round(slope_ms(chain_dense_sweep, (packed, gdense)), 3)

    res["uniq_logical_frac"] = round(
        float(np.mean([np.unique(np.asarray(b.ids)).size / m for b in batches])), 4
    )
    _watchdog.cancel()


if __name__ == "__main__":
    main()
