#!/usr/bin/env python
"""Held-out-AUC data-scaling study: close (or bound) the gap to the oracle.

VERDICT r1 left the held-out quality claim unfinished at 0.826 vs the 0.911
planted-oracle ceiling at 2.4M rows, with the data-scaling argument (150k →
0.649, 600k → 0.712, 2.4M → 0.826) "plausible but unfinished".  This script
extends the curve (default out to ~9.6M rows on the identical task and
settings) and writes one JSON artifact with every point next to the oracle
ceiling, so the claim "the residual gap is sample volume, not trainer
quality" is a committed measurement, not an assertion.

Usage:
  python tools/scaling_study.py [--rows 2400000,4800000,9600000]
                                [--epochs 4] [--out scaling_study.json]

Each point: generate train split (fixed test split, 50k rows, seed 1),
train the real `train()` driver with binary_cache, record the best
validation AUC from the JSONL metrics, report vs the oracle AUC (the
planted model scoring the same held-out rows — the ceiling ANY learner has
on Bernoulli(sigmoid(score)) labels).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

FIELDS, K_HIDDEN, SPREAD, VOCAB = 39, 4, 3.0, 1 << 14


def oracle_auc(path):
    import gen_synthetic

    from fast_tffm_tpu.data.native import best_parser
    from fast_tffm_tpu.data.pipeline import batch_stream
    from fast_tffm_tpu.metrics import auc

    labels, scores = [], []
    for b, w in batch_stream(
        [path], batch_size=8192, vocabulary_size=VOCAB, max_nnz=FIELDS,
        parser=best_parser(),
    ):
        n = int((w > 0).sum())
        scores.append(
            gen_synthetic.planted_score(
                np.asarray(b.ids)[:n], b.vals[:n], factor_num=K_HIDDEN
            )
        )
        labels.append(b.labels[:n])
    return auc(np.concatenate(labels), np.concatenate(scores))


def train_point(td, rows, te, epochs, lr, bs):
    import gen_synthetic

    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.training import train

    tr = os.path.join(td, f"tr_{rows}.libsvm")
    t0 = time.time()
    gen_synthetic.generate(
        tr, rows=rows, fields=FIELDS, vocab=VOCAB, seed=0,
        factor_num=K_HIDDEN, spread=SPREAD,
    )
    gen_secs = time.time() - t0
    metrics = os.path.join(td, f"metrics_{rows}.jsonl")
    cfg = Config(
        model="fm",
        factor_num=8,
        vocabulary_size=VOCAB,
        model_file=os.path.join(td, f"m_{rows}.ckpt"),
        train_files=(tr,),
        validation_files=(te,),
        epoch_num=epochs,
        batch_size=bs,
        learning_rate=lr,
        log_every=10**9,
        metrics_path=metrics,
        binary_cache=True,
    ).validate()
    t0 = time.time()
    train(cfg, log=lambda *_: None)
    train_secs = time.time() - t0
    with open(metrics) as f:
        aucs = [
            r["validation_auc"] for r in map(json.loads, f) if "validation_auc" in r
        ]
    # Free the big splits as we go (10M rows of text+fmb is ~10 GB).
    for suffix in ("", ".fmb"):
        try:
            os.remove(tr + suffix)
        except OSError:
            pass
    return max(aucs), {"gen_secs": round(gen_secs, 1), "train_secs": round(train_secs, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default="2400000,4800000,9600000")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--out", default="scaling_study.json")
    args = ap.parse_args()

    import gen_synthetic

    points = []
    with tempfile.TemporaryDirectory() as td:
        te = os.path.join(td, "te.libsvm")
        gen_synthetic.generate(
            te, rows=50_000, fields=FIELDS, vocab=VOCAB, seed=1,
            factor_num=K_HIDDEN, spread=SPREAD,
        )
        oracle = oracle_auc(te)
        print(json.dumps({"oracle_auc": round(oracle, 5)}), flush=True)
        for rows in [int(r) for r in args.rows.split(",")]:
            auc_v, timing = train_point(
                td, rows, te, args.epochs, args.lr, args.batch
            )
            point = {
                "rows": rows,
                "heldout_auc": round(auc_v, 5),
                "oracle_auc": round(oracle, 5),
                "gap": round(oracle - auc_v, 5),
                "lift_vs_oracle": round((auc_v - 0.5) / (oracle - 0.5), 4),
                **timing,
            }
            points.append(point)
            print(json.dumps(point), flush=True)

    artifact = {
        "study": "held-out AUC vs training rows (planted Zipf CTR task, "
        f"FM k=8, vocab=2^14, {FIELDS} fields, spread={SPREAD}, "
        f"epochs={args.epochs}, lr={args.lr}, batch={args.batch})",
        "r1_points": [
            {"rows": 150_000, "heldout_auc": 0.649},
            {"rows": 600_000, "heldout_auc": 0.712},
            {"rows": 2_400_000, "heldout_auc": 0.826},
        ],
        "points": points,
    }
    from fast_tffm_tpu.telemetry import write_json_artifact

    write_json_artifact(args.out, artifact, sort_keys=False)
    print(json.dumps({"written": args.out}))


if __name__ == "__main__":
    main()
