#!/usr/bin/env python
"""Decompose cfg3p (packed FFM, D=89, P=1) — where does the time go?

VERDICT r4 #4: cfg3p measured 372k ex/s (0.74× the bar) and no DESIGN
entry pins where its step time goes.  Stages, marginal-slope timed
(probe_scale_ops methodology) at the cfg3p knee shape (B=32768, N=22
fields, vocab 2^20, lane-packed P=1):

  gather      packed wide gather [M, 128] (89/128 useful lanes)
  fwd         FFM score (one-hot einsum T build + cross + diag)
  fwdbwd      score + hand-offs through jax.grad
  upd_dense / upd_sorted / upd_compact
              the three packed sparse tails at this shape
  step_f32 / step_bf16
              the full jitted step, f32 vs bfloat16 interaction einsums
              (models/ffm.py compute_dtype), interleaved A/B

Writes PROBE_FFM_r05.json.
"""

import dataclasses
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=2700, what="probe_ffm.py")

import jax
import jax.numpy as jnp
import numpy as np

import bench
import bench_all
from fast_tffm_tpu.models import FFMModel
from fast_tffm_tpu.optim import AdagradState
from fast_tffm_tpu.ops.packed_table import (
    LANES,
    packed_compact_adagrad_update,
    packed_dense_adagrad_update,
    packed_gather,
    packed_rows,
    packed_sparse_adagrad_update,
    rows_per_tile,
)
from fast_tffm_tpu.trainer import (
    TrainState,
    batch_loss,
    init_packed_state,
    make_packed_train_step,
)

B = 32768
F = 22
K = 4
VOCAB = 1 << 20


def slope_ms(jfn, args, k_lo=2, k_hi=8, reps=3):
    """Marginal ms per application.  Device arrays ride as jit ARGUMENTS —
    a closed-over table embeds a 537 MB HLO constant and hangs the remote
    compiler (observed; probe_scale_ops.py same note)."""
    float(jfn(k_lo, *args))
    float(jfn(k_hi, *args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jfn(k_lo, *args))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(jfn(k_hi, *args))
        t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (k_hi - k_lo))
    return round(best * 1e3, 3)


def main():
    model = FFMModel(vocabulary_size=VOCAB, num_fields=F, factor_num=K)
    d = model.row_dim  # 89
    p = rows_per_tile(d)  # 1
    vp = packed_rows(VOCAB, d)
    m = B * F

    rng = np.random.default_rng(0)
    batch = bench_all.make_batch(rng, B, F, VOCAB, num_fields=F)
    state = init_packed_state(model, jax.random.key(0))
    table, accum = state.table, state.table_opt.accum
    g_rows = jnp.asarray(rng.normal(size=(B, F, d)).astype(np.float32) * 1e-3)

    out = {"B": B, "F": F, "vocab": VOCAB, "d": d, "p": p, "vp": vp, "m": m}

    @partial(jax.jit, static_argnums=(0,))
    def chain_gather(k, table, ids):
        def body(i, s):
            rows = packed_gather(table, jnp.bitwise_xor(ids, i), d)
            return s + rows[0, 0, 0]
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["gather_ms"] = slope_ms(chain_gather, (table, batch.ids))
    print("gather_ms", out["gather_ms"], flush=True)

    rows0 = packed_gather(table, batch.ids, d)

    @partial(jax.jit, static_argnums=(0,))
    def chain_fwd(k, rows0, batch):
        def body(i, s):
            sc = model.score(rows0 + 0 * jnp.float32(i), {}, batch)
            return s + sc[0]
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["fwd_ms"] = slope_ms(chain_fwd, (rows0, batch))
    print("fwd_ms", out["fwd_ms"], flush=True)

    @partial(jax.jit, static_argnums=(0,))
    def chain_fwdbwd(k, table, batch):
        def body(i, s):
            rows = packed_gather(table, jnp.bitwise_xor(batch.ids, i), d)
            (_, dl), (gr, _) = jax.value_and_grad(
                partial(batch_loss, model), argnums=(0, 1), has_aux=True
            )(rows, {}, batch)
            return s + gr[0, 0, 0] + dl
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["fwdbwd_ms"] = slope_ms(chain_fwdbwd, (table, batch))
    print("fwdbwd_ms", out["fwdbwd_ms"], flush=True)

    for tag, fn in (
        ("upd_dense", packed_dense_adagrad_update),
        ("upd_compact", packed_compact_adagrad_update),
        ("upd_sorted", packed_sparse_adagrad_update),
    ):
        @partial(jax.jit, static_argnums=(0,))
        def chain_upd(k, table, accum, ids, g_rows, fn=fn):
            def body(i, carry):
                t, a, s = carry
                t, a = fn(t, a, jnp.bitwise_xor(ids, i), g_rows, 0.01)
                return t, a, s + t[0, 0]
            t, a, s = jax.lax.fori_loop(0, k, body, (table, accum, jnp.float32(0)))
            return s + a[0, 0]

        out[f"{tag}_ms"] = slope_ms(chain_upd, (table, accum, batch.ids, g_rows))
        print(tag, out[f"{tag}_ms"], flush=True)

    # Whole-step A/B: f32 vs bf16 interaction einsums, interleaved.
    batches = [bench_all.make_batch(rng, B, F, VOCAB, num_fields=F) for _ in range(4)]
    s32 = init_packed_state(model, jax.random.key(1))
    step32 = make_packed_train_step(model, 0.05, "auto")
    mb = dataclasses.replace(model, compute_dtype="bfloat16")
    sbf = init_packed_state(mb, jax.random.key(1))
    stepbf = make_packed_train_step(mb, 0.05, "auto")

    # bench.interleaved_measure takes ONE step with two batch sets; here
    # the A/B is two different executables, so alternate tight windows by
    # hand (same-session medians, the same drift defense).
    def rate(step, st):
        st, _ = step(st, batches[0])
        bench.forced_sync(st)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(10):
                st, _ = step(st, batches[i % 4])
            bench.forced_sync(st)
            best = min(best, time.perf_counter() - t0)
        return st, B * 10 / best

    # Alternate windows: 32, bf, 32, bf — medians, same-session.
    r32s, rbfs = [], []
    s32, _ = rate(step32, s32)  # warm + first window discarded into list
    sbf, _ = rate(stepbf, sbf)
    for _ in range(3):
        s32, r = rate(step32, s32)
        r32s.append(r)
        sbf, r = rate(stepbf, sbf)
        rbfs.append(r)
    out["step_f32_rate"] = round(sorted(r32s)[1], 1)
    out["step_bf16_rate"] = round(sorted(rbfs)[1], 1)
    out["bf16_speedup_x"] = round(out["step_bf16_rate"] / out["step_f32_rate"], 3)

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "PROBE_FFM_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print("wrote", path)


if __name__ == "__main__":
    main()
