#!/usr/bin/env python
"""Decompose the giant-vocab (201M-row) packed train step op by op.

Round-5 question: at vocab 2^24 the packed dense step runs 478k ex/s at
B=16384 (34 ms), but at 201M rows EVERY tail strategy — rows, sorted,
dense-G's compact successor — lands at 79-105k (160-200 ms).  The update
strategy barely matters, so something else scales with VP.  This probe
times marginal fori_loop slopes (bench.forced_sync methodology) for each
stage at the scale shape and the headline shape in the SAME session:

  gather     packed_gather [M, 128] wide gather + slice extraction
  fwdbwd     full forward + backward, NO table update
  bitmap     touched scatter + cumsum over [VP] + slot gather (compact's
             VP-dependent piece)
  update     full packed_compact_adagrad_update
  step       the whole jitted train step (compact), bench-measured

All device arrays are passed as jit ARGUMENTS — a closed-over table would
embed GB-sized constants in the HLO and hang the remote compiler
(observed this session).  Writes PROBE_SCALE_OPS_r05.json.
"""

import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=3000, what="probe_scale_ops.py")

import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import make_batch, zipf_ids
from fast_tffm_tpu.models import FMModel
from fast_tffm_tpu.optim import AdagradState
from fast_tffm_tpu.ops.packed_table import (
    LANES,
    packed_compact_adagrad_update,
    packed_gather,
    packed_rows,
    rows_per_tile,
)
from fast_tffm_tpu.trainer import TrainState, batch_loss, make_packed_train_step

BATCH = 16384
NNZ = 39
K = 8
D = 1 + K
P = rows_per_tile(D)


def slope_ms(jfn, args, k_lo=2, k_hi=8, reps=3):
    """Marginal ms per application: jfn(k, *args) chains k applications
    behind a value dependency; slope = (t_hi − t_lo) / (k_hi − k_lo)."""
    float(jfn(k_lo, *args))  # compile both
    float(jfn(k_hi, *args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jfn(k_lo, *args))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(jfn(k_hi, *args))
        t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (k_hi - k_lo))
    return round(best * 1e3, 3)


def probe_vocab(vocab: int) -> dict:
    rng = np.random.default_rng(0)
    model = FMModel(vocabulary_size=vocab, factor_num=K, order=2)
    vp = packed_rows(vocab, D)
    m = BATCH * NNZ
    k_cap = min(vp, m)

    table = jax.jit(
        lambda key: jax.random.uniform(key, (vp, LANES), jnp.float32, -0.01, 0.01)
    )(jax.random.key(0))
    accum = jnp.full((vp, P), 0.1, jnp.float32)
    batch = make_batch(zipf_ids(rng, (BATCH, NNZ), vocab), 0)
    ids = batch.ids
    g_rows = jnp.asarray(
        np.random.default_rng(1).normal(size=(BATCH, NNZ, D)).astype(np.float32)
        * 1e-3
    )

    out = {"vocab": vocab, "vp": vp, "m": m}

    @partial(jax.jit, static_argnums=(0,))
    def chain_gather(k, table, ids):
        def body(i, s):
            rows = packed_gather(table, jnp.bitwise_xor(ids, i), D)
            return s + rows[0, 0, 0]
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["gather_ms"] = slope_ms(chain_gather, (table, ids))
    print(vocab, "gather_ms", out["gather_ms"], flush=True)

    @partial(jax.jit, static_argnums=(0,))
    def chain_fwdbwd(k, table, batch):
        def body(i, s):
            rows = packed_gather(table, jnp.bitwise_xor(batch.ids, i), D)
            (_, dl), (gr, _) = jax.value_and_grad(
                partial(batch_loss, model), argnums=(0, 1), has_aux=True
            )(rows, {}, batch)
            return s + gr[0, 0, 0] + dl
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["fwdbwd_ms"] = slope_ms(chain_fwdbwd, (table, batch))
    print(vocab, "fwdbwd_ms", out["fwdbwd_ms"], flush=True)

    flat = ids.reshape(-1)

    @partial(jax.jit, static_argnums=(0,))
    def chain_bitmap(k, flat):
        def body(i, s):
            fl = jnp.bitwise_xor(flat, i)
            phys = (fl // P).astype(jnp.int32)
            touched = jnp.zeros((vp,), jnp.int8).at[phys].set(1, mode="drop")
            csum = jnp.cumsum(touched, dtype=jnp.int32)
            slot = csum[jnp.minimum(phys, vp - 1)] - 1
            return s + jnp.float32(slot[0])
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["bitmap_cumsum_ms"] = slope_ms(chain_bitmap, (flat,))
    print(vocab, "bitmap_cumsum_ms", out["bitmap_cumsum_ms"], flush=True)

    @partial(jax.jit, static_argnums=(0,))
    def chain_update(k, table, accum, ids, g_rows):
        def body(i, carry):
            t, a, s = carry
            t, a = packed_compact_adagrad_update(
                t, a, jnp.bitwise_xor(ids, i), g_rows, 0.01
            )
            return t, a, s + t[0, 0]
        t, a, s = jax.lax.fori_loop(0, k, body, (table, accum, jnp.float32(0)))
        return s + a[0, 0]

    out["compact_update_ms"] = slope_ms(chain_update, (table, accum, ids, g_rows))
    print(vocab, "compact_update_ms", out["compact_update_ms"], flush=True)

    # Whole step, bench-measured for the same-session anchor.
    import bench

    state = TrainState(table=table, table_opt=AdagradState(accum), dense={},
                       dense_opt=AdagradState({}), step=jnp.zeros((), jnp.int32))
    step = make_packed_train_step(model, 0.01, "compact")
    batches = [make_batch(zipf_ids(rng, (BATCH, NNZ), vocab), i) for i in range(4)]
    state, rate = bench.measure(step, state, batches, iters=20, batch_size=BATCH)
    out["step_rate_per_chip"] = round(rate / jax.device_count(), 1)
    out["step_ms"] = round(BATCH / rate * 1e3 * jax.device_count(), 2)
    del state, table, accum
    return out


def main():
    res = {}
    for vocab in (1 << 24, 201_326_592):
        res[str(vocab)] = probe_vocab(vocab)
        print(vocab, "->", res[str(vocab)], flush=True)
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "PROBE_SCALE_OPS_r05.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    main()
