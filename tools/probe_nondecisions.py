#!/usr/bin/env python
"""Round-4 re-measurement of the artifact-era non-decisions (VERDICT r3 #2).

Every DESIGN §6/§8.5 bullet measured before the round-3 methodology
correction is re-stamped here with value-synced, same-window INTERLEAVED
A/B timing (bench.forced_sync closes every window):

  1. bf16 parameter table — rows layout (the original "2× slower" claim)
     and the packed layout, where bf16 halves table bytes on both the
     wide gather and the dense Adagrad sweep.
  2. dedup-before-forward-gather — plus the structural note: under jit
     the unique-row count must be a STATIC shape, so "gather fewer rows"
     is only realizable as gather-same-count-sorted; the measurable
     lever is sorted-id locality, which is what we time.
  3. [V, 2D] (and packed [VP, 256]) table+accum interleave for the
     sorted sparse tail's RMW.
  4. XLA wide-gather effective bandwidth (the "Pallas gather has no
     headroom" input: if XLA's gather already rides the HBM roof there
     is no headroom; if not, the gap IS the Pallas headroom).

Prints one JSON dict; partial results flush on exit.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=3000, what="probe_nondecisions.py")

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from bench import make_batch, zipf_ids
from fast_tffm_tpu.models import FMModel
from fast_tffm_tpu.optim import AdagradState, sparse_adagrad_update
from fast_tffm_tpu.ops.packed_table import (
    LANES,
    packed_dense_adagrad_update,
    packed_gather,
    rows_per_tile,
)
from fast_tffm_tpu.trainer import (
    TrainState,
    batch_loss,
    init_packed_state,
    make_packed_train_step,
)

NNZ = 39
K = 8
B = 16384


def _sync(state):
    """forced_sync for TrainState OR (table, ...) tuples: value-fetch a
    slice of the first table-like array so the chained updates must have
    landed (bench.forced_sync rationale)."""
    t = state.table if hasattr(state, "table") else state[0]
    return float(jnp.sum(jax.lax.dynamic_slice_in_dim(t, 0, 2, axis=0)))


def interleaved(step_a, state_a, step_b, state_b, batches, iters, rounds=3):
    """Median per-step seconds for A and B, timed in ALTERNATING windows
    of the same session (A B A B ...), each window closed by a value
    fetch that depends on the final table (forced_sync)."""
    state_a, _ = step_a(state_a, batches[0])
    _sync(state_a)
    state_b, _ = step_b(state_b, batches[0])
    _sync(state_b)
    ta, tb = [], []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for i in range(iters):
            state_a, _ = step_a(state_a, batches[i % len(batches)])
        _sync(state_a)
        ta.append((time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for i in range(iters):
            state_b, _ = step_b(state_b, batches[i % len(batches)])
        _sync(state_b)
        tb.append((time.perf_counter() - t0) / iters)
    return float(np.median(ta)), float(np.median(tb)), state_a, state_b


def main():
    rng = np.random.default_rng(0)
    res = {"device": jax.devices()[0].device_kind}
    import atexit

    atexit.register(lambda: print(json.dumps(res), flush=True))

    def mark(name):
        print(f"# section {name} @ {time.strftime('%H:%M:%S')}", file=sys.stderr, flush=True)

    only = os.environ.get("PROBE_ONLY", "").split(",")
    only = [x for x in only if x]

    def want(name):
        return not only or name in only

    def flush():
        # Incremental flush: a hung backend call can eat SIGINT/SIGTERM
        # before atexit runs (observed: section-3 scatter hang lost every
        # completed section's numbers) — persist after EVERY section.
        print(json.dumps(res), flush=True)

    if want("1a"):
        run_1a(res, rng, mark, flush)
    if want("1b"):
        run_1b(res, rng, mark, flush)
    if want("2") or want("4") or want("3"):
        run_24(res, rng, mark, flush, want)
    return


def run_1a(res, rng, mark, flush):
    mark("1a rows_bf16")
    # ---------------- 1a. bf16 table, rows layout ----------------
    # Mini-step isolating what the original claim was about: the [V, D]
    # gather + RMW sparse-Adagrad path with the table stored bf16 vs f32
    # (accumulator stays f32 in both arms — Adagrad accumulation in bf16
    # would change semantics, not just layout).
    vocab = 1 << 20
    d = 1 + K
    key = jax.random.key(0)
    table_f32 = jax.random.normal(key, (vocab, d), jnp.float32) * 0.01

    def mini_step(state, batch):
        # The two arms differ ONLY in the stored table dtype (carried by
        # the state); compute is f32 in both — the same jitted callable
        # retraces per input dtype.
        table, acc = state
        rows = table[batch.ids].astype(jnp.float32)  # [B, N, D]
        g_rows = rows * batch.vals[..., None]  # cheap stand-in gradient
        new_table, opt = sparse_adagrad_update(
            table.astype(jnp.float32), AdagradState(acc), batch.ids, g_rows, 0.01
        )
        return (new_table.astype(table.dtype), opt.accum), jnp.sum(rows[0, 0])

    step_f32 = step_bf16 = jax.jit(mini_step, donate_argnums=(0,))
    batches = [make_batch(zipf_ids(rng, (B, NNZ), vocab), i) for i in range(8)]
    sa = (table_f32, jnp.full((vocab, d), 0.1, jnp.float32))
    sb = (table_f32.astype(jnp.bfloat16), jnp.full((vocab, d), 0.1, jnp.float32))
    f32_s, bf16_s, sa, sb = interleaved(step_f32, sa, step_bf16, sb, batches, 6)
    res["rows_bf16"] = {
        "f32_ms": round(f32_s * 1e3, 2),
        "bf16_ms": round(bf16_s * 1e3, 2),
        "bf16_over_f32": round(bf16_s / f32_s, 3),
    }
    flush()
    del sa, sb



def run_1b(res, rng, mark, flush):
    mark("1b packed_bf16")
    # ---------------- 1b. bf16 table, packed layout, dense update -------
    # The packed table in bf16 halves the bytes of the wide forward
    # gather AND the dense sweep's table read/write; G and the
    # accumulator stay f32 (same Adagrad semantics).
    vocab = 1 << 24
    d = 1 + K
    model = FMModel(vocabulary_size=vocab, factor_num=K, order=2)
    batches = [make_batch(zipf_ids(rng, (B, NNZ), vocab), 100 + i) for i in range(8)]

    def packed_bf16_body(state, batch):
        rows = packed_gather(state.table, batch.ids, d).astype(jnp.float32)
        grad_fn = jax.value_and_grad(
            partial(batch_loss, model), argnums=(0, 1), has_aux=True
        )
        (_, data_loss), (g_rows, _) = grad_fn(rows, state.dense, batch)
        table_f32, accum = packed_dense_adagrad_update(
            state.table.astype(jnp.float32),
            state.table_opt.accum,
            batch.ids,
            g_rows,
            0.01,
        )
        return (
            TrainState(
                table_f32.astype(jnp.bfloat16),
                AdagradState(accum),
                state.dense,
                state.dense_opt,
                state.step + 1,
            ),
            data_loss,
        )

    step_f32 = make_packed_train_step(model, 0.01, "dense")
    step_bf16 = jax.jit(packed_bf16_body, donate_argnums=(0,))
    sa = init_packed_state(model, jax.random.key(0))
    sb0 = init_packed_state(model, jax.random.key(0))
    sb = TrainState(
        sb0.table.astype(jnp.bfloat16),
        sb0.table_opt,
        sb0.dense,
        sb0.dense_opt,
        sb0.step,
    )
    del sb0
    f32_s, bf16_s, sa, sb = interleaved(step_f32, sa, step_bf16, sb, batches, 6)
    res["packed_bf16_dense"] = {
        "f32_ms": round(f32_s * 1e3, 2),
        "bf16_ms": round(bf16_s * 1e3, 2),
        "bf16_over_f32": round(bf16_s / f32_s, 3),
        "f32_ex_s": round(B / f32_s, 1),
        "bf16_ex_s": round(B / bf16_s, 1),
    }
    flush()
    del sa, sb



def run_24(res, rng, mark, flush, want):
    # Shared setup for sections 2/4/3 (same packed array + slope helper).
    vocab = 1 << 24
    d = 1 + K
    p = rows_per_tile(d)
    vp = -(-vocab // p)
    packed = jax.random.normal(jax.random.key(1), (vp, LANES), jnp.float32)
    flat = zipf_ids(rng, (B * NNZ,), vocab).astype(np.int32)

    def slope_ms(fn, arrays, k_lo=2, k_hi=10, reps=3):
        """Marginal ms per op: k applications carry-chained inside ONE
        jit, cost from the (k_hi - k_lo) difference — single-shot
        timings on this tunnel include a ~100 ms fetch RTT and are
        garbage (measured; an early version of section 2 "measured" a
        1.6 TB/s gather that way)."""
        jfn = jax.jit(fn, static_argnums=(1,))
        for k in (k_lo, k_hi):
            float(jfn(arrays, k))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jfn(arrays, k_lo))
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(jfn(arrays, k_hi))
            t_hi = time.perf_counter() - t0
            best = min(best, (t_hi - t_lo) / (k_hi - k_lo))
        return best * 1e3

    if want("2"):
        mark("2 gather locality")
        # ------------ 2. dedup / sorted-id locality on the wide gather --
        # Under jit the unique count is dynamic => a real dedup cannot
        # shrink the gather's static shape.  The realizable lever is
        # LOCALITY: gather the same M rows with ids pre-sorted
        # (duplicates adjacent) vs raw order.
        phys_raw = jnp.asarray(flat // p)
        phys_sorted = jnp.asarray(np.sort(flat // p))

        def gather_k(arrays, k):
            table, phys = arrays

            def body(i, acc):
                return acc + jnp.sum(table[(phys + i) % vp])  # shift kills caching

            return jax.lax.fori_loop(0, k, body, jnp.float32(0))

        raw_ms = slope_ms(gather_k, (packed, phys_raw))
        sorted_ms = slope_ms(gather_k, (packed, phys_sorted))
        res["gather_sorted_locality"] = {
            "raw_ms": round(raw_ms, 2),
            "sorted_ms": round(sorted_ms, 2),
            "sorted_over_raw": round(sorted_ms / raw_ms, 3),
            "rows": int(flat.size),
            "unique_rows": int(np.unique(flat // p).size),
            "payload_mb": round(flat.size * LANES * 4 / 1e6, 1),
            "raw_gbps": round(flat.size * LANES * 4 / (raw_ms / 1e3) / 1e9, 1),
            "sorted_gbps": round(flat.size * LANES * 4 / (sorted_ms / 1e3) / 1e9, 1),
        }
        flush()

    if want("4"):
        mark("4 dense copy")
        # ------------ 4. Pallas-gather headroom input -------------------
        # (same slope method): dense elementwise GB/s vs the gather's
        # GB/s — the gap is the most a hand gather kernel could recover.
        def sweep_k(arrays, k):
            (x,) = arrays

            def body(i, acc):
                # Barrier: without it XLA folds the k multiplies into one
                # pass over memory (measured: a NEGATIVE slope), and the
                # "k sweeps" measure one.
                return jax.lax.optimization_barrier(acc * 1.000001)

            return jnp.sum(jax.lax.fori_loop(0, k, body, x)[0])

        x = jax.random.normal(jax.random.key(2), (vp, LANES), jnp.float32)
        sweep_ms = slope_ms(sweep_k, (x,))
        dense_gbps = 2 * vp * LANES * 4 / (sweep_ms / 1e3) / 1e9
        res["dense_sweep_ms"] = round(sweep_ms, 2)
        res["dense_copy_gbps"] = round(dense_gbps, 1)
        raw_gbps = res.get("gather_sorted_locality", {}).get("raw_gbps")
        if raw_gbps:
            res["gather_headroom_x"] = round(dense_gbps / raw_gbps, 2)
        flush()
        del x

    if want("3"):
        if os.environ.get("PROBE_MERGED") != "1":
            # Section 3 hangs this backend (a [M, 256]-lane scatter-set
            # at M=639k wedged the device >15 min, unkillable mid-call)
            # — opt in with PROBE_MERGED=1 after the hang is understood.
            return
        mark("3 merged rmw")
        # ------------ 3. merged table+accum interleave ------------------
        # Sorted sparse tail: split [VP,128]+[VP,128] (2 RMW gathers + 2
        # scatters) vs ONE merged [VP,256] array (1 gather + 1 scatter
        # of 256-lane rows).  Mini-kernel isolating just the RMW tail.
        m = 160_000  # small: the full 639k wedged the backend (see gate)
        ids_b = [jnp.asarray(zipf_ids(rng, (m,), vocab) // p) for i in range(4)]
        gsum = jax.random.normal(jax.random.key(2), (m, LANES), jnp.float32) * 1e-3

        def rmw_split(state, uphys):
            tab, acc = state
            cur = tab[uphys]
            a = acc[uphys]
            a2 = a + gsum * gsum
            new = cur - 0.01 * gsum / jnp.sqrt(a2)
            return (tab.at[uphys].set(new), acc.at[uphys].set(a2)), new[0, 0]

        def rmw_merged(merged, uphys):
            cur = merged[uphys]  # [M, 256]
            a2 = cur[:, LANES:] + gsum * gsum
            new = cur[:, :LANES] - 0.01 * gsum / jnp.sqrt(a2)
            return merged.at[uphys].set(jnp.concatenate([new, a2], -1)), new[0, 0]

        js = jax.jit(rmw_split, donate_argnums=(0,))
        jm = jax.jit(rmw_merged, donate_argnums=(0,))
        ss = (
            jax.random.normal(jax.random.key(3), (vp, LANES), jnp.float32),
            jnp.full((vp, LANES), 0.1, jnp.float32),
        )
        sm = jnp.concatenate(
            [
                jax.random.normal(jax.random.key(3), (vp, LANES), jnp.float32),
                jnp.full((vp, LANES), 0.1, jnp.float32),
            ],
            -1,
        )
        ts_, tm_ = [], []
        ss, _ = js(ss, ids_b[0])  # compile (donated input rebinds to output)
        float(ss[0][0, 0])
        sm, _ = jm(sm, ids_b[0])
        float(sm[0, 0])
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(4):
                ss, v = js(ss, ids_b[i])
            float(ss[0][0, 0])
            ts_.append((time.perf_counter() - t0) / 4)
            t0 = time.perf_counter()
            for i in range(4):
                sm, v = jm(sm, ids_b[i])
            float(sm[0, 0])
            tm_.append((time.perf_counter() - t0) / 4)
        split_s, merged_s = float(np.median(ts_)), float(np.median(tm_))
        res["merged_rmw"] = {
            "split_ms": round(split_s * 1e3, 2),
            "merged_ms": round(merged_s * 1e3, 2),
            "merged_over_split": round(merged_s / split_s, 3),
        }
        flush()


if __name__ == "__main__":
    main()
