#!/usr/bin/env python
"""Render a telemetry JSONL run into a human report; gate regressions.

The consumer half of fast_tffm_tpu/telemetry.py: training/predict/serving
write enveloped records (one run_id, ``kind`` ∈ telemetry.SCHEMAS) to
``metrics_path``; this tool turns that stream back into answers —
*how fast was it, was the input or the device the bottleneck, did it
recompile / stall / diverge, and is it worse than the last run?*

    python tools/report.py RUN.jsonl                    # markdown → stdout
    python tools/report.py RUN.jsonl --out REPORT.md
    python tools/report.py RUN.jsonl --compare BASE.jsonl [--threshold 0.15]

``--compare`` prints per-metric deltas and exits **1** when RUN's median
throughput is degraded more than ``--threshold`` (fraction) vs BASE — a
bench gate: wire two instrumented runs into CI and a slowdown fails the
build.  ``--strict`` additionally fails on NEW steady-state recompiles,
stalls, or anomalies.  Exit 2 = unusable input.

Stdlib-only on purpose: the report must render on a machine that can't
even import jax (e.g. triaging a stall dump from a wedged TPU host).

bench.py also imports ``write_bench_report`` to drop a REPORT_rNN.md
next to each BENCH_rNN.json (delta table vs the previous round).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys

_BLOCKS = "▁▂▃▄▅▆▇█"


def spark(vals) -> str:
    """Unicode sparkline (empty-safe)."""
    vals = [v for v in vals if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _BLOCKS[0] * len(vals)
    return "".join(
        _BLOCKS[int((v - lo) / (hi - lo) * (len(_BLOCKS) - 1))] for v in vals
    )


def _fmt(v, nd=1) -> str:
    if v is None:
        return "–"
    if isinstance(v, float):
        if abs(v) < 10:  # losses/AUCs need the decimals, rates don't
            nd = max(nd, 4)
        return f"{v:,.{nd}f}"
    return f"{v:,}"


def _fmt_bucket_occupancy(sv: dict) -> str:
    """``64: 0.81 (5917/7296 rows)`` per bucket, smallest bucket first.

    Occupancy per bucket, not just the blended mean: the blend hides a
    single oversized bucket absorbing every coalesced flush (the 0.286
    pathology) behind healthy-looking small-bucket numbers."""
    occ = sv.get("bucket_occupancy") or {}
    rows = sv.get("bucket_rows") or {}
    padded = sv.get("bucket_padded_rows") or {}
    parts = []
    for k in sorted(occ, key=lambda x: int(x)):
        r = rows.get(k)
        p = padded.get(k)
        total = (r + p) if isinstance(r, int) and isinstance(p, int) else None
        detail = f" ({r}/{total} rows)" if total is not None else ""
        parts.append(f"{k}: {occ[k]}{detail}")
    return ", ".join(parts) or "–"


def _fmt_bytes(v) -> str:
    if v is None:
        return "–"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{v} B"
        v /= 1024
    return f"{v:.1f} TiB"


def load_run(path: str) -> list[dict]:
    """All parseable JSONL records; raises ValueError when nothing is."""
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
    if not records:
        raise ValueError(f"{path}: no parseable JSONL records")
    if bad:
        print(f"note: {path}: skipped {bad} malformed line(s)", file=sys.stderr)
    return records


def _by_kind(records):
    out = {}
    for r in records:
        out.setdefault(r.get("kind", "step"), []).append(r)
    return out


def summarize(records: list[dict]) -> dict:
    """Flatten one run's records into the metrics the report (and the
    compare gate) speaks: throughput stats, loss endpoints, input-path
    shares, event counts, memory peaks.

    MetricsLogger appends, so successive runs with one config share one
    file; pooling them would fake convergence (loss_first from run 1,
    loss_final from run 2) and corrupt the compare gate both ways — only
    the LAST run_id is summarized, with a stderr note."""
    distinct = list(dict.fromkeys(r.get("run_id") for r in records if r.get("run_id")))
    if len(distinct) > 1:
        last = distinct[-1]
        print(
            f"note: {len(distinct)} runs appended in this file; "
            f"reporting only the last (run_id {last})",
            file=sys.stderr,
        )
        records = [r for r in records if r.get("run_id") == last]
    kinds = _by_kind(records)
    s: dict = {
        "run_ids": distinct[-1:],
        "runs_in_file": len(distinct),
        "kinds": {k: len(v) for k, v in sorted(kinds.items())},
    }
    ts = [r["t"] for r in records if isinstance(r.get("t"), (int, float))]
    s["duration_s"] = round(max(ts) - min(ts), 3) if ts else None

    train = kinds.get("train", [])
    rates = [
        r["examples_per_sec"]
        for r in train
        if isinstance(r.get("examples_per_sec"), (int, float))
    ]
    losses = [r["loss"] for r in train if isinstance(r.get("loss"), (int, float))]
    s["train_windows"] = len(train)
    s["steps"] = max((r.get("step", 0) for r in records), default=0)
    s["throughput_timeline"] = rates
    s["throughput_median"] = round(statistics.median(rates), 1) if rates else None
    s["throughput_final"] = rates[-1] if rates else None
    s["loss_timeline"] = losses
    s["loss_first"] = losses[0] if losses else None
    s["loss_final"] = losses[-1] if losses else None

    inputs = kinds.get("input", [])

    def _wsum(key):
        tot = n = 0.0
        for r in inputs:
            v, items = r.get(key), r.get("input_items", 0)
            if isinstance(v, (int, float)) and items:
                tot += v * items
                n += items
        return (tot, n)

    parse_tot, parse_items = _wsum("parse_ms")
    h2d_tot, h2d_items = _wsum("h2d_ms")
    s["parse_ms_mean"] = round(parse_tot / parse_items, 3) if parse_items else None
    s["h2d_ms_mean"] = round(h2d_tot / h2d_items, 3) if h2d_items else None
    wires = [
        r["wire_bytes_per_step"]
        for r in inputs
        if isinstance(r.get("wire_bytes_per_step"), (int, float))
    ]
    s["wire_bytes_per_step"] = int(statistics.median(wires)) if wires else None
    depths = [
        r["prefetch_queue_depth"]
        for r in inputs
        if isinstance(r.get("prefetch_queue_depth"), (int, float))
    ]
    s["prefetch_queue_depth_mean"] = (
        round(sum(depths) / len(depths), 2) if depths else None
    )
    # Input-vs-compute split: host input work (parse + pack/H2D) as a
    # share of the run's wall clock.  >1 window is overlap (prefetch
    # thread) — still an honest "the host was this busy feeding" number.
    if s["duration_s"]:
        busy_ms = parse_tot + h2d_tot
        s["input_time_share"] = round(busy_ms / 1e3 / s["duration_s"], 3)
    else:
        s["input_time_share"] = None

    compiles = kinds.get("compile", [])
    s["warmup_compiles"] = sum(
        r.get("compiles", 0) for r in compiles if r.get("warmup")
    )
    s["steady_compiles"] = sum(
        r.get("compiles", 0) for r in compiles if not r.get("warmup")
    )
    s["steady_compile_steps"] = [r.get("step") for r in compiles if not r.get("warmup")]

    s["stalls"] = len(kinds.get("stall", []))
    s["stall_events"] = [
        {
            "step": r.get("step"),
            "since_last_step_s": r.get("since_last_step_s"),
            "classification": r.get("classification"),
            "prefetch_queue_depth": r.get("prefetch_queue_depth"),
        }
        for r in kinds.get("stall", [])
    ]
    s["anomalies"] = len(kinds.get("anomaly", []))
    s["anomaly_events"] = [
        {
            "step": r.get("step"),
            "event": r.get("event"),
            "loss": r.get("loss"),
            "first_nonfinite": r.get("first_nonfinite"),
        }
        for r in kinds.get("anomaly", [])
    ]
    # Resilience layer: fault / restart records (resilience.py) plus
    # rollback anomalies (on_nan = rollback recovery decisions).
    s["rollbacks"] = sum(
        1 for r in kinds.get("anomaly", []) if r.get("event") == "rollback"
    )
    faults = kinds.get("fault", [])
    s["faults"] = len(faults)
    s["fault_events"] = [
        {
            "step": r.get("step"),
            "event": r.get("event"),
            "exit_code": r.get("exit_code"),
            "signal": r.get("signal"),
            "what": r.get("what"),
        }
        for r in faults[:50]  # bounded: a retry storm must not bloat the report
    ]
    restarts = kinds.get("restart", [])
    s["restarts"] = len(restarts)
    s["restart_events"] = [
        {
            "attempt": r.get("attempt"),
            "exit_code": r.get("exit_code"),
            "backoff_s": r.get("backoff_s"),
            "mttr_s": r.get("mttr_s"),
        }
        for r in restarts
    ]
    mttrs = [
        r["mttr_s"] for r in restarts if isinstance(r.get("mttr_s"), (int, float))
    ]
    s["mttr_s_median"] = round(statistics.median(mttrs), 3) if mttrs else None
    s["mttr_s_max"] = round(max(mttrs), 3) if mttrs else None

    ckpts = kinds.get("ckpt", [])
    s["ckpt_saves"] = len(ckpts)
    s["ckpt_modes"] = {}
    for r in ckpts:
        mode = r.get("mode") or "?"
        s["ckpt_modes"][mode] = s["ckpt_modes"].get(mode, 0) + 1
    stall_ms = sum(
        r["train_stall_ms"]
        for r in ckpts
        if isinstance(r.get("train_stall_ms"), (int, float))
    )
    s["ckpt_stall_ms_total"] = round(stall_ms, 1) if ckpts else None
    s["ckpt_bytes_total"] = (
        sum(r.get("bytes") or 0 for r in ckpts) if ckpts else None
    )
    s["ckpt_rows_written"] = (
        sum(max(0, r.get("rows_written") or 0) for r in ckpts) if ckpts else None
    )
    # Checkpoint stall as a share of wall clock — the companion number to
    # input_time_share: together they say where the loop's non-compute
    # time went (feeding the chip vs saving the model).
    s["ckpt_stall_share"] = (
        round(stall_ms / 1e3 / s["duration_s"], 4)
        if ckpts and s["duration_s"]
        else (0.0 if s["duration_s"] else None)
    )

    # Per-host breakdown (multi-process pods): every record carries the
    # emitting host's process_index in the envelope; merging the per-host
    # JSONL files (report.py RUN.jsonl RUN.p1.jsonl ...) for one run_id
    # yields per-host throughput / stall / MTTR columns.  Host-LEVEL
    # faults (a peer's heartbeat lost, straggler kills, host crashes) are
    # counted separately — --compare --strict gates on them.
    procs = sorted(
        {
            r.get("process_index", 0)
            for r in records
            if isinstance(r.get("process_index"), int)
        }
    )
    s["hosts"] = {}
    host_faults = 0
    for r in kinds.get("stall", []):
        if str(r.get("classification", "")).startswith("host-"):
            host_faults += 1
    for r in faults:
        if r.get("event") in ("crash", "straggler_kill") and r.get("process") is not None:
            host_faults += 1
    s["host_faults"] = host_faults
    if len(procs) > 1:
        for p in procs:
            sub = [r for r in records if r.get("process_index", 0) == p]
            sk = _by_kind(sub)
            p_rates = [
                r["examples_per_sec"]
                for r in sk.get("train", [])
                if isinstance(r.get("examples_per_sec"), (int, float))
            ]
            p_mttrs = [
                r["mttr_s"]
                for r in sk.get("restart", [])
                if isinstance(r.get("mttr_s"), (int, float))
            ]
            s["hosts"][p] = {
                "records": len(sub),
                "throughput_median": (
                    round(statistics.median(p_rates), 1) if p_rates else None
                ),
                "steady_compiles": sum(
                    r.get("compiles", 0)
                    for r in sk.get("compile", [])
                    if not r.get("warmup")
                ),
                "stalls": len(sk.get("stall", [])),
                "faults": len(sk.get("fault", [])),
                "restarts": len(sk.get("restart", [])),
                "mttr_s_median": (
                    round(statistics.median(p_mttrs), 3) if p_mttrs else None
                ),
            }

    # Deep observability (ISSUE 9).  profile: one record per measured
    # compiled program (XLA cost analysis) — the MEASURED column DESIGN
    # §8.5's "re-measure only with evidence" reads next to the modeled
    # HBM floor; datastats: sampled id-traffic statistics; freshness:
    # publish→applied / publish→first-scored SLO samples.
    s["profiled_programs"] = {}
    for r in kinds.get("profile", []):
        if r.get("program") and r.get("program") != "trace":
            s["profiled_programs"][r["program"]] = {
                "bytes_accessed": r.get("bytes_accessed"),
                "flops": r.get("flops"),
                "examples": r.get("examples"),
                "bytes_per_example": r.get("bytes_per_example"),
                "modeled_hbm_bytes": r.get("modeled_hbm_bytes"),
            }
    s["trace_events"] = [
        {"step": r.get("step"), "event": r.get("event"), "trace_dir": r.get("trace_dir")}
        for r in kinds.get("profile", [])
        if r.get("program") == "trace"
    ]
    t = s["profiled_programs"].get("train_step") or {}
    s["measured_bytes_per_example"] = t.get("bytes_per_example")

    ds = kinds.get("datastats", [])
    s["datastats_samples"] = len(ds)
    dedups = [r["dedup_ratio"] for r in ds if isinstance(r.get("dedup_ratio"), (int, float))]
    s["dedup_ratio_mean"] = round(sum(dedups) / len(dedups), 4) if dedups else None
    s["datastats_last"] = (
        {
            k: ds[-1].get(k)
            for k in (
                "ids", "unique", "dedup_ratio", "rows_seen", "rows_seen_frac",
                "hh_k", "hh_topk_mass", "gather_bytes", "dedup_gather_bytes",
                "projected_gather_savings_frac",
            )
        }
        if ds
        else None
    )

    def _pctl(vals, q):
        # Nearest-rank over the (small, per-run) record lists; stdlib-only
        # like everything in this tool.
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 3)

    fresh = kinds.get("freshness", [])
    applied = [
        r["publish_to_applied_ms"]
        for r in fresh
        if isinstance(r.get("publish_to_applied_ms"), (int, float))
    ]
    scored = [
        r["publish_to_first_scored_ms"]
        for r in fresh
        if isinstance(r.get("publish_to_first_scored_ms"), (int, float))
    ]
    s["freshness_samples"] = len(fresh)
    s["freshness_applied_p50_ms"] = _pctl(applied, 0.50)
    s["freshness_applied_p99_ms"] = _pctl(applied, 0.99)
    s["freshness_scored_p50_ms"] = _pctl(scored, 0.50)
    s["freshness_scored_p99_ms"] = _pctl(scored, 0.99)
    # The gate metric: end-to-end (first-scored) p99 where measured, the
    # applied p99 otherwise (router-only streams see staging, not
    # scoring).  `is None`, not truthiness: a clamped-to-0 scored p99
    # (publisher clock ahead) is still the scored metric, and silently
    # swapping to applied would gate two different metrics against each
    # other in --compare.
    s["freshness_p99_ms"] = (
        s["freshness_scored_p99_ms"]
        if s["freshness_scored_p99_ms"] is not None
        else s["freshness_applied_p99_ms"]
    )

    mems = kinds.get("mem", [])
    s["host_rss_peak_bytes"] = max(
        (r["host_rss_peak_bytes"] for r in mems if r.get("host_rss_peak_bytes")),
        default=None,
    )
    s["device_peak_bytes"] = max(
        (r["device_peak_bytes"] for r in mems if r.get("device_peak_bytes")),
        default=None,
    )

    vals = kinds.get("validation", [])
    s["validation_aucs"] = [
        r["validation_auc"] for r in vals if r.get("validation_auc") is not None
    ]

    # Online-learning loop (ISSUE 11).  quality: the rolling backtest's
    # per-hour held-out AUC for the online trainer vs its batch-retrain
    # reference (tools/backtest.py); the worst-hour gap (batch − online)
    # is the single-run regression signal --strict gates on.  soak: the
    # sustained-soak harness's sentinel ticks (tools/soak.py) — any
    # ok=false tick is a soak failure.
    qual = kinds.get("quality", [])
    s["quality_hours"] = len(qual)
    s["quality_auc_by_hour"] = [
        {
            "hour": r.get("hour"),
            "online": r.get("auc_online"),
            "batch": r.get("auc_batch"),
        }
        for r in qual
    ]
    q_on = [r["auc_online"] for r in qual if isinstance(r.get("auc_online"), (int, float))]
    q_ba = [r["auc_batch"] for r in qual if isinstance(r.get("auc_batch"), (int, float))]
    s["quality_auc_online_mean"] = (
        round(sum(q_on) / len(q_on), 5) if q_on else None
    )
    s["quality_auc_batch_mean"] = round(sum(q_ba) / len(q_ba), 5) if q_ba else None
    gaps = [
        r["auc_batch"] - r["auc_online"]
        for r in qual
        if isinstance(r.get("auc_online"), (int, float))
        and isinstance(r.get("auc_batch"), (int, float))
    ]
    s["quality_auc_gap_max"] = round(max(gaps), 5) if gaps else None
    soak = kinds.get("soak", [])
    s["soak_ticks"] = len(soak)
    s["soak_failures"] = sum(1 for r in soak if r.get("ok") is False)
    s["soak_failed_phases"] = sorted(
        {str(r.get("phase")) for r in soak if r.get("ok") is False}
    )
    serving = kinds.get("serving", [])
    s["serving_last"] = serving[-1] if serving else None

    # Replicated serving tier (ISSUE 8): per-replica kind=serving records
    # carry a `replica` envelope key (each replica worker writes its own
    # JSONL sibling — pass them all: report.py RUN.jsonl RUN.jsonl.r0 ...).
    # Replica-level faults/restarts come from the router's records.
    by_replica: dict = {}
    for r in serving:
        if isinstance(r.get("replica"), int):
            by_replica[r["replica"]] = r  # last snapshot wins per replica
    s["serving_replicas"] = by_replica
    s["deadline_drops"] = (
        sum(r.get("deadline_drops") or 0 for r in by_replica.values())
        if by_replica
        else (s["serving_last"] or {}).get("deadline_drops")
    )
    sheds: dict[str, int] = {}
    class_p99: dict[str, float] = {}
    snaps = list(by_replica.values()) or ([s["serving_last"]] if s["serving_last"] else [])
    for r in snaps:
        for k, v in (r.get("sheds_by_class") or {}).items():
            sheds[k] = sheds.get(k, 0) + v
        for k, h in (r.get("class_total_ms") or {}).items():
            p99 = h.get("p99")
            if isinstance(p99, (int, float)):
                # Max across replicas: the SLO is only as good as the
                # worst replica a client can land on.
                class_p99[k] = max(class_p99.get(k, 0.0), p99)
    s["sheds_by_class"] = sheds
    s["class_p99_ms"] = class_p99
    # Per-bucket padding waste, summed across replicas (the occupancy
    # fix's observability: bucket chosen AFTER the coalescing flush).
    b_rows: dict[str, int] = {}
    b_padded: dict[str, int] = {}
    for r in snaps:
        for k, v in (r.get("bucket_rows") or {}).items():
            b_rows[k] = b_rows.get(k, 0) + (v or 0)
        for k, v in (r.get("bucket_padded_rows") or {}).items():
            b_padded[k] = b_padded.get(k, 0) + (v or 0)
    s["bucket_rows"] = b_rows
    s["bucket_padded_rows"] = b_padded
    s["bucket_occupancy"] = {
        k: round(r / (r + b_padded.get(k, 0)), 4)
        for k, r in sorted(b_rows.items(), key=lambda kv: int(kv[0]))
        if r + b_padded.get(k, 0) > 0
    }
    s["replica_faults"] = sum(
        1 for r in faults if isinstance(r.get("replica"), int)
    )
    s["replica_fault_events"] = [
        {
            "replica": r.get("replica"),
            "event": r.get("event"),
            "exit_code": r.get("exit_code"),
        }
        for r in faults
        if isinstance(r.get("replica"), int)
    ][:50]
    rep_restarts = [
        r for r in restarts if isinstance(r.get("replica"), int)
    ]
    s["replica_restarts"] = len(rep_restarts)
    rep_mttrs = [
        r["mttr_s"] for r in rep_restarts if isinstance(r.get("mttr_s"), (int, float))
    ]
    s["replica_mttr_s_median"] = (
        round(statistics.median(rep_mttrs), 3) if rep_mttrs else None
    )
    s["replica_mttr_s_max"] = round(max(rep_mttrs), 3) if rep_mttrs else None
    # Tiered parameter store (ISSUE 12; paramstore/): per-log-window
    # residency records.  The hit rate and miss bytes are the two numbers
    # --compare --strict gates on: a hot set gone stale (hit rate down)
    # or a staging path gone fat (miss bytes up) are regressions even
    # when raw throughput holds.
    tier = kinds.get("tiering", [])
    s["tiering_windows"] = len(tier)
    hits = [r["hit_rate"] for r in tier if isinstance(r.get("hit_rate"), (int, float))]
    s["tier_hit_rate_mean"] = round(sum(hits) / len(hits), 4) if hits else None
    mbytes = [
        r["miss_bytes_per_step"]
        for r in tier
        if isinstance(r.get("miss_bytes_per_step"), (int, float))
    ]
    s["tier_miss_bytes_per_step"] = (
        int(statistics.median(mbytes)) if mbytes else None
    )
    s["tier_miss_rows"] = sum(r.get("miss_rows") or 0 for r in tier) if tier else None
    s["tier_writeback_rows"] = (
        sum(r.get("writeback_rows") or 0 for r in tier) if tier else None
    )
    wb_ms = sum(
        (r.get("writeback_ms") or 0) + (r.get("apply_ms") or 0) for r in tier
    )
    s["tier_writeback_ms_total"] = round(wb_ms, 1) if tier else None
    # Writeback stall share: staging D2H + store applies as a fraction of
    # wall clock — the tiered sibling of ckpt_stall_share.
    s["tier_writeback_share"] = (
        round(wb_ms / 1e3 / s["duration_s"], 4)
        if tier and s["duration_s"]
        else None
    )
    s["tier_restages"] = sum(r.get("restages") or 0 for r in tier) if tier else None
    s["tier_pending_rows_max"] = (
        max((r.get("pending_rows") or 0) for r in tier) if tier else None
    )
    s["tier_hot_rows"] = tier[-1].get("hot_rows") if tier else None
    predict = kinds.get("predict", [])
    s["predict_last"] = predict[-1] if predict else None
    summary = kinds.get("summary", [])
    s["summary_record"] = summary[-1] if summary else None
    return s


def render(s: dict, title: str = "run") -> str:
    """One markdown report per run.  Sections appear only when the run
    actually produced that kind — a predict run isn't padded with empty
    train tables."""
    L = [f"# Telemetry report — {title}", ""]
    L.append(f"- run_id: `{', '.join(s['run_ids']) or '?'}`")
    L.append(f"- duration: {_fmt(s['duration_s'], 1)} s, max step {s['steps']}")
    L.append(
        "- records: "
        + ", ".join(f"{k}={n}" for k, n in s["kinds"].items())
    )
    L.append("")
    if s["throughput_timeline"]:
        L += ["## Throughput", ""]
        L.append(f"`{spark(s['throughput_timeline'])}` examples/sec per log window")
        L.append(
            f"- median {_fmt(s['throughput_median'])}, "
            f"final {_fmt(s['throughput_final'])}, "
            f"min {_fmt(min(s['throughput_timeline']))}, "
            f"max {_fmt(max(s['throughput_timeline']))}"
        )
        L.append("")
    if s["loss_timeline"]:
        L += ["## Loss", ""]
        L.append(f"`{spark(s['loss_timeline'])}`")
        L.append(f"- first {s['loss_first']} → final {s['loss_final']}")
        if s["validation_aucs"]:
            L.append(
                "- validation AUC per epoch: "
                + ", ".join(f"{a:.5f}" for a in s["validation_aucs"])
            )
        L.append("")
    if any(
        s[k] is not None
        for k in ("parse_ms_mean", "h2d_ms_mean", "input_time_share")
    ):
        L += ["## Input path", ""]
        L.append(f"- parse {_fmt(s['parse_ms_mean'], 3)} ms/item, "
                 f"pack+H2D {_fmt(s['h2d_ms_mean'], 3)} ms/item")
        L.append(f"- wire bytes/step: {_fmt(s['wire_bytes_per_step'], 0)}")
        L.append(
            f"- prefetch queue depth mean: {_fmt(s['prefetch_queue_depth_mean'], 2)} "
            "(≈0 = producer-bound, at cap = consumer-bound)"
        )
        if s["input_time_share"] is not None:
            L.append(
                f"- host input time ≈ {100 * s['input_time_share']:.1f}% of wall "
                "clock (overlapped via prefetch)"
            )
        if s.get("ckpt_stall_share") is not None:
            L.append(
                f"- checkpoint stall ≈ {100 * s['ckpt_stall_share']:.1f}% of wall "
                "clock (train-loop time blocked on saves)"
            )
        L.append("")
    if s.get("ckpt_saves"):
        L += ["## Checkpointing", ""]
        modes = ", ".join(f"{m}={n}" for m, n in sorted(s["ckpt_modes"].items()))
        L.append(
            f"- {s['ckpt_saves']} save(s) ({modes}), "
            f"{_fmt_bytes(s['ckpt_bytes_total'])} written, "
            f"{_fmt(s['ckpt_rows_written'], 0)} rows"
        )
        L.append(
            f"- train-loop stall {_fmt(s['ckpt_stall_ms_total'])} ms total"
            + (
                f" ({100 * s['ckpt_stall_share']:.1f}% of wall clock)"
                if s.get("ckpt_stall_share") is not None
                else ""
            )
        )
        L.append("")
    if s.get("tiering_windows"):
        L += ["## Parameter store (tiered)", ""]
        L.append(
            f"- hot tier {_fmt(s['tier_hot_rows'], 0)} rows, "
            f"hit rate {_fmt(100 * (s['tier_hit_rate_mean'] or 0), 2)}% of "
            "gather slots"
        )
        L.append(
            f"- miss bytes/step {_fmt_bytes(s['tier_miss_bytes_per_step'])} "
            f"({_fmt(s['tier_miss_rows'], 0)} staged rows total)"
        )
        L.append(
            f"- writeback: {_fmt(s['tier_writeback_rows'], 0)} rows, "
            f"{_fmt(s['tier_writeback_ms_total'])} ms stall"
            + (
                f" ({100 * s['tier_writeback_share']:.1f}% of wall clock)"
                if s.get("tier_writeback_share") is not None
                else ""
            )
        )
        L.append(
            f"- coherency restages: {s['tier_restages']}, pending peak "
            f"{_fmt(s['tier_pending_rows_max'], 0)} rows"
        )
        L.append("")
    L += ["## Events", ""]
    L.append(
        f"- compiles: {s['warmup_compiles']} warmup, "
        f"**{s['steady_compiles']} steady-state**"
        + (
            f" (at steps {s['steady_compile_steps']})"
            if s["steady_compiles"]
            else ""
        )
    )
    L.append(f"- stalls: {s['stalls']}")
    for e in s["stall_events"]:
        L.append(
            f"  - step {e['step']}: {e['classification']}, "
            f"{e['since_last_step_s']}s without a step, "
            f"queue depth {e['prefetch_queue_depth']}"
        )
    L.append(f"- anomalies: {s['anomalies']}")
    for e in s["anomaly_events"]:
        L.append(
            f"  - step {e['step']}: {e['event']} loss={e['loss']}"
            + (
                f" first_nonfinite={e['first_nonfinite']}"
                if e.get("first_nonfinite")
                else ""
            )
        )
    L.append("")
    if s.get("faults") or s.get("restarts") or s.get("rollbacks"):
        L += ["## Resilience", ""]
        L.append(
            f"- faults: {s['faults']}, restarts: {s['restarts']}, "
            f"rollbacks: {s['rollbacks']}"
        )
        for e in s["fault_events"]:
            detail = ", ".join(
                f"{k}={v}"
                for k, v in e.items()
                if k not in ("step", "event") and v is not None
            )
            L.append(
                f"  - step {e['step']}: fault {e['event']}"
                + (f" ({detail})" if detail else "")
            )
        for e in s["restart_events"]:
            L.append(
                f"  - restart #{e['attempt']}: child rc {e['exit_code']}, "
                f"backoff {e['backoff_s']}s, MTTR {e['mttr_s']}s"
            )
        if s.get("mttr_s_median") is not None:
            L.append(
                f"- MTTR (crash → first new progress): median "
                f"{s['mttr_s_median']}s, max {s['mttr_s_max']}s"
            )
        L.append("")
    if s.get("hosts"):
        L += ["## Hosts (per-process breakdown)", ""]
        L.append(
            "| host | records | ex/s median | steady compiles | stalls | "
            "faults | restarts | MTTR median |"
        )
        L.append("|---:|---:|---:|---:|---:|---:|---:|---:|")
        for p, h in sorted(s["hosts"].items()):
            L.append(
                f"| {p} | {h['records']} | {_fmt(h['throughput_median'])} | "
                f"{h['steady_compiles']} | {h['stalls']} | {h['faults']} | "
                f"{h['restarts']} | {_fmt(h['mttr_s_median'], 3)} |"
            )
        if s.get("host_faults"):
            L.append(f"- host-level faults: {s['host_faults']}")
        L.append("")
    if s.get("profiled_programs") or s.get("trace_events"):
        L += ["## Profiling (measured vs modeled)", ""]
        if s["profiled_programs"]:
            L.append(
                "| program | measured bytes/dispatch | modeled HBM floor | "
                "× floor | bytes/example | MFLOPs |"
            )
            L.append("|---|---:|---:|---:|---:|---:|")
            for name, p in sorted(s["profiled_programs"].items()):
                meas, mod = p.get("bytes_accessed"), p.get("modeled_hbm_bytes")
                ratio = (
                    f"{meas / mod:.2f}"
                    if isinstance(meas, (int, float))
                    and isinstance(mod, (int, float))
                    and mod > 0
                    else "–"
                )
                fl = p.get("flops")
                L.append(
                    f"| {name} | {_fmt_bytes(meas)} | {_fmt_bytes(mod)} | "
                    f"{ratio} | {_fmt(p.get('bytes_per_example'), 1)} | "
                    f"{_fmt(round(fl / 1e6, 2) if isinstance(fl, (int, float)) else None)} |"
                )
            L.append(
                "- measured = XLA cost analysis (bytes accessed) of the "
                "compiled program; modeled = the driver's irreducible-HBM "
                "floor for the same dispatch (DESIGN §8.5: re-measure only "
                "with evidence — this is the evidence column)"
            )
        for e in s.get("trace_events", []):
            L.append(
                f"- trace {e['event']} at step {e['step']} → `{e['trace_dir']}`"
            )
        L.append("")
    if s.get("datastats_samples"):
        d = s["datastats_last"] or {}
        L += ["## Id-traffic statistics", ""]
        L.append(
            f"- {s['datastats_samples']} sampled windows; dedup ratio "
            f"(unique/slots) mean {_fmt(s['dedup_ratio_mean'], 4)}, "
            f"last {_fmt(d.get('dedup_ratio'), 4)}"
        )
        L.append(
            f"- last window: {_fmt(d.get('ids'))} id slots, "
            f"{_fmt(d.get('unique'))} unique; rows seen (cumulative) "
            f"{_fmt(d.get('rows_seen'))} ({_fmt(d.get('rows_seen_frac'), 4)} of vocab)"
        )
        if d.get("hh_topk_mass") is not None:
            L.append(
                f"- heavy hitters: top-{d.get('hh_k')} sketch buckets carry "
                f"{100 * d['hh_topk_mass']:.1f}% of gather traffic (upper "
                "bound — collisions overstate)"
            )
        if d.get("projected_gather_savings_frac") is not None:
            L.append(
                f"- projected dedup-before-gather saving: "
                f"{100 * d['projected_gather_savings_frac']:.1f}% of gather bytes "
                f"({_fmt_bytes(d.get('gather_bytes'))} → "
                f"{_fmt_bytes(d.get('dedup_gather_bytes'))} per dispatch)"
            )
        L.append("")
    if s.get("freshness_samples"):
        L += ["## Freshness (publish → serving)", ""]
        L.append(
            f"- {s['freshness_samples']} reload(s): publish→applied p50/p99 "
            f"{_fmt(s['freshness_applied_p50_ms'])}/"
            f"{_fmt(s['freshness_applied_p99_ms'])} ms"
        )
        if s.get("freshness_scored_p50_ms") is not None:
            L.append(
                f"- publish→first-scored-with-new-rows p50/p99 "
                f"{_fmt(s['freshness_scored_p50_ms'])}/"
                f"{_fmt(s['freshness_scored_p99_ms'])} ms"
            )
        L.append("")
    if s.get("quality_hours"):
        L += ["## Online quality (rolling backtest)", ""]
        L.append("| hour | online AUC | batch-retrain AUC | gap |")
        L.append("|---:|---:|---:|---:|")
        for row in s["quality_auc_by_hour"]:
            gap = (
                round(row["batch"] - row["online"], 5)
                if isinstance(row.get("online"), (int, float))
                and isinstance(row.get("batch"), (int, float))
                else None
            )
            L.append(
                f"| {row['hour']} | {row['online']} | {row['batch']} | {gap} |"
            )
        L.append(
            f"- mean online {s['quality_auc_online_mean']} vs batch "
            f"{s['quality_auc_batch_mean']}; worst-hour gap "
            f"{s['quality_auc_gap_max']}"
        )
        L.append("")
    if s.get("soak_ticks"):
        L += ["## Soak sentinels", ""]
        L.append(
            f"- {s['soak_ticks']} sentinel tick(s), "
            f"{s['soak_failures']} failed"
            + (
                f" (phases: {', '.join(s['soak_failed_phases'])})"
                if s.get("soak_failed_phases")
                else ""
            )
        )
        L.append("")
    L += ["## Memory", ""]
    L.append(f"- host RSS peak: {_fmt_bytes(s['host_rss_peak_bytes'])}")
    L.append(f"- device live-buffer peak: {_fmt_bytes(s['device_peak_bytes'])}")
    L.append("")
    if s["predict_last"]:
        p = s["predict_last"]
        L += [
            "## Predict",
            "",
            f"- {_fmt(p.get('examples'))} examples at "
            f"{_fmt(p.get('examples_per_sec'))} examples/sec",
            "",
        ]
    if s["serving_last"]:
        sv = s["serving_last"]
        L += ["## Serving (last snapshot)", ""]
        L.append(
            f"- requests {_fmt(sv.get('requests'))}, rejected "
            f"{_fmt(sv.get('rejected'))}, flushes {_fmt(sv.get('flushes'))}, "
            f"occupancy {sv.get('batch_occupancy')}"
        )
        for stage in ("queue_ms", "compute_ms", "total_ms"):
            h = sv.get(stage) or {}
            L.append(
                f"- {stage}: p50 {h.get('p50')}, p95 {h.get('p95')}, "
                f"p99 {h.get('p99')}, max {h.get('max')}"
            )
        if sv.get("bucket_occupancy"):
            L.append("- per-bucket occupancy: " + _fmt_bucket_occupancy(sv))
        L.append("")
    if s.get("serving_replicas") or s.get("replica_faults"):
        L += ["## Serving resilience (replicated tier)", ""]
        if s.get("serving_replicas"):
            L.append(
                "| replica | requests | rows scored | ex/s | deadline_drops "
                "| sheds | p99 ms |"
            )
            L.append("|---:|---:|---:|---:|---:|---:|---:|")
            for rep, sv in sorted(s["serving_replicas"].items()):
                rows = sv.get("rows")
                qps = (
                    round(rows / s["duration_s"], 1)
                    if isinstance(rows, (int, float)) and s["duration_s"]
                    else None
                )
                shed_n = sum((sv.get("sheds_by_class") or {}).values())
                L.append(
                    f"| {rep} | {_fmt(sv.get('requests'))} | {_fmt(rows)} | "
                    f"{_fmt(qps)} | {_fmt(sv.get('deadline_drops'))} | "
                    f"{_fmt(shed_n)} | "
                    f"{(sv.get('total_ms') or {}).get('p99')} |"
                )
        if s.get("sheds_by_class"):
            L.append(
                "- sheds by class: "
                + ", ".join(f"{k}={v}" for k, v in sorted(s["sheds_by_class"].items()))
            )
        if s.get("class_p99_ms"):
            L.append(
                "- per-class p99 (worst replica): "
                + ", ".join(
                    f"{k}={v}ms" for k, v in sorted(s["class_p99_ms"].items())
                )
            )
        if s.get("bucket_occupancy"):
            L.append(
                "- per-bucket occupancy (all replicas): "
                + _fmt_bucket_occupancy(
                    {
                        "bucket_rows": s.get("bucket_rows"),
                        "bucket_padded_rows": s.get("bucket_padded_rows"),
                        "bucket_occupancy": s["bucket_occupancy"],
                    }
                )
            )
        L.append(
            f"- replica faults: {s.get('replica_faults', 0)}, restarts: "
            f"{s.get('replica_restarts', 0)}"
        )
        for e in s.get("replica_fault_events", []):
            L.append(
                f"  - replica {e['replica']}: {e['event']}"
                + (f" (rc={e['exit_code']})" if e.get("exit_code") is not None else "")
            )
        if s.get("replica_mttr_s_median") is not None:
            L.append(
                f"- replica MTTR (death detected → healthy again): median "
                f"{s['replica_mttr_s_median']}s, max {s['replica_mttr_s_max']}s"
            )
        L.append("")
    return "\n".join(L)


# -- compare (the bench gate) --------------------------------------------

# (metric key, human label, higher_is_better)
_GATE_METRICS = [
    ("throughput_median", "median examples/sec", True),
    ("throughput_final", "final examples/sec", True),
    ("loss_final", "final loss", False),
    ("steady_compiles", "steady-state compiles", False),
    ("stalls", "stalls", False),
    ("anomalies", "anomalies", False),
    ("faults", "faults", False),
    ("restarts", "restarts", False),
    ("rollbacks", "rollbacks", False),
    ("deadline_drops", "serving deadline drops", False),
    ("replica_faults", "serving replica faults", False),
    ("replica_restarts", "serving replica restarts", False),
    ("host_rss_peak_bytes", "host RSS peak", False),
    ("device_peak_bytes", "device mem peak", False),
    ("ckpt_stall_share", "ckpt stall share", False),
    ("measured_bytes_per_example", "measured HBM bytes/example", False),
    ("dedup_ratio_mean", "id dedup ratio (unique/slots)", False),
    ("freshness_p99_ms", "freshness p99 (ms)", False),
    ("quality_auc_online_mean", "backtest online AUC (mean)", True),
    ("quality_auc_gap_max", "backtest worst-hour AUC gap", False),
    ("soak_failures", "failed soak sentinel ticks", False),
    ("tier_hit_rate_mean", "paramstore hot-tier hit rate", True),
    ("tier_miss_bytes_per_step", "paramstore miss bytes/step", False),
    ("tier_restages", "paramstore coherency restages", False),
]


def compare(run: dict, base: dict, threshold: float, strict: bool = False):
    """Per-metric deltas (run vs base) + the gate verdict.

    Returns (markdown, regressions: list[str]).  The hard gate is median
    throughput degraded by more than ``threshold`` (fraction); ``strict``
    adds NEW steady compiles / stalls / anomalies to the gate.
    """
    L = ["# Telemetry compare — run vs base", ""]
    L.append("| metric | base | run | delta |")
    L.append("|---|---:|---:|---:|")
    regressions = []
    for key, label, _hib in _GATE_METRICS:
        a, b = run.get(key), base.get(key)
        if a is None and b is None:
            continue
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and b:
            delta = f"{(a - b) / abs(b) * 100:+.1f}%"
        elif isinstance(a, (int, float)) and isinstance(b, (int, float)):
            delta = f"{a - b:+g}"
        else:
            delta = "–"
        L.append(f"| {label} | {_fmt(b)} | {_fmt(a)} | {delta} |")
    L.append("")
    a, b = run.get("throughput_median"), base.get("throughput_median")
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) and b > 0:
        drop = (b - a) / b
        if drop > threshold:
            regressions.append(
                f"median throughput degraded {drop * 100:.1f}% "
                f"(> {threshold * 100:.0f}% threshold): {b} -> {a}"
            )
    elif a is None and isinstance(b, (int, float)) and b > 0:
        # A gate that passes when the candidate produced NO throughput
        # records would wave through the worst regression of all: a run
        # that crashed or wedged before its first log window.
        regressions.append(
            "run has no train throughput records (base has "
            f"{b}) — crashed/stalled before the first log window?"
        )
    if strict:
        for key, label in (
            ("steady_compiles", "steady-state compiles"),
            ("stalls", "stalls"),
            ("anomalies", "anomalies"),
            ("faults", "faults"),
            ("restarts", "restarts"),
            ("rollbacks", "rollbacks"),
            ("host_faults", "host-level faults"),
            ("replica_faults", "serving replica faults"),
        ):
            if (run.get(key) or 0) > (base.get(key) or 0):
                regressions.append(
                    f"new {label}: {base.get(key) or 0} -> {run.get(key) or 0}"
                )
        # Per-class serving p99 SLO gate: a class whose worst-replica p99
        # degraded past the threshold regresses even if the aggregate
        # (dominated by the bulk class) still looks fine — priority
        # classes are exactly the ones a mean would hide.
        for k, bp in (base.get("class_p99_ms") or {}).items():
            rp = (run.get("class_p99_ms") or {}).get(k)
            if (
                isinstance(rp, (int, float))
                and isinstance(bp, (int, float))
                and bp > 0
                and rp > bp * (1 + threshold)
            ):
                regressions.append(
                    f"serving class {k!r} p99 regressed "
                    f"{(rp - bp) / bp * 100:.1f}% (> {threshold * 100:.0f}%): "
                    f"{bp}ms -> {rp}ms"
                )
        # The ISSUE-9 SLO gates: a freshness p99 regression (the model is
        # measurably staler at the replicas) and a measured-bytes-per-
        # example regression (the compiled step moves more HBM per row
        # than the base did — the evidence ledger as an enforced budget).
        # Freshness gates FLAVOR-MATCHED: scored-vs-scored when both runs
        # measured end-to-end, else applied-vs-applied — a run that only
        # saw staging must never be gated against one that saw scoring
        # (applied <= scored by construction, so a mixed pair would mask
        # a real regression or invent a spurious one).
        if (
            run.get("freshness_scored_p99_ms") is not None
            and base.get("freshness_scored_p99_ms") is not None
        ):
            fresh_gate = (
                "freshness_scored_p99_ms", "freshness p99 (publish→first-scored)",
            )
        else:
            fresh_gate = (
                "freshness_applied_p99_ms", "freshness p99 (publish→applied)",
            )
        for key, label, floor in (
            (*fresh_gate, 1.0),
            ("measured_bytes_per_example", "measured HBM bytes/example", 0.0),
        ):
            rv, bv = run.get(key), base.get(key)
            if (
                isinstance(rv, (int, float))
                and isinstance(bv, (int, float))
                and bv > floor
                and rv > bv * (1 + threshold)
            ):
                regressions.append(
                    f"{label} regressed {(rv - bv) / bv * 100:.1f}% "
                    f"(> {threshold * 100:.0f}%): {bv} -> {rv}"
                )
        # Online-quality gates (ISSUE 11).  Against a BASE with backtest
        # records: the online trainer's mean held-out AUC must not drop
        # more than the threshold fraction.  Within the RUN alone: the
        # worst-hour gap to its OWN batch-retrain reference must stay
        # under the threshold (read as absolute AUC points here — AUC is
        # already a [0.5, 1] fraction), and any failed soak sentinel tick
        # is a regression outright.
        rq, bq = run.get("quality_auc_online_mean"), base.get("quality_auc_online_mean")
        if (
            isinstance(rq, (int, float))
            and isinstance(bq, (int, float))
            and bq > 0
            and rq < bq * (1 - threshold)
        ):
            regressions.append(
                f"online backtest AUC regressed {(bq - rq) / bq * 100:.1f}% "
                f"(> {threshold * 100:.0f}%): {bq} -> {rq}"
            )
        gap = run.get("quality_auc_gap_max")
        if isinstance(gap, (int, float)) and gap > threshold:
            regressions.append(
                f"online trainer trails its batch-retrain reference by "
                f"{gap:.4f} AUC at the worst hour (> {threshold:.2f})"
            )
        if (run.get("soak_failures") or 0) > 0:
            regressions.append(
                f"{run['soak_failures']} soak sentinel tick(s) failed "
                f"(phases: {', '.join(run.get('soak_failed_phases') or [])})"
            )
        # Tiered-parameter-store gates (ISSUE 12): a hot-tier HIT-RATE
        # drop past the threshold (the residency decision got worse — the
        # staging path absorbs gathers the hot tier should) and a
        # MISS-BYTES-per-step increase past it (the wire/staging traffic
        # the hit rate is supposed to bound).  Both only when both runs
        # are tiered.
        rh, bh = run.get("tier_hit_rate_mean"), base.get("tier_hit_rate_mean")
        if (
            isinstance(rh, (int, float))
            and isinstance(bh, (int, float))
            and bh > 0
            and rh < bh * (1 - threshold)
        ):
            regressions.append(
                f"paramstore hit rate regressed {(bh - rh) / bh * 100:.1f}% "
                f"(> {threshold * 100:.0f}%): {bh} -> {rh}"
            )
        rm, bm = (
            run.get("tier_miss_bytes_per_step"),
            base.get("tier_miss_bytes_per_step"),
        )
        if (
            isinstance(rm, (int, float))
            and isinstance(bm, (int, float))
            and bm > 0
            and rm > bm * (1 + threshold)
        ):
            regressions.append(
                f"paramstore miss bytes/step regressed "
                f"{(rm - bm) / bm * 100:.1f}% (> {threshold * 100:.0f}%): "
                f"{bm} -> {rm}"
            )
        # Checkpoint stall share regression: the run spends a meaningfully
        # larger fraction of wall clock blocked on saves than the base did.
        # The 1% absolute floor keeps end-of-run sync saves (every run has
        # one) from flagging noise on short runs.
        rs = run.get("ckpt_stall_share") or 0.0
        bs = base.get("ckpt_stall_share") or 0.0
        if rs > 0.01 and rs > bs * (1 + threshold) + 0.002:
            regressions.append(
                f"ckpt stall share regressed: {bs:.3f} -> {rs:.3f} of wall clock"
            )
    if regressions:
        L.append("**REGRESSED:**")
        L += [f"- {r}" for r in regressions]
    else:
        L.append(f"OK — no regression beyond the {threshold * 100:.0f}% threshold.")
    L.append("")
    return "\n".join(L), regressions


# -- serving bench (loadgen artifacts) ------------------------------------


def load_bench_serve(path: str) -> dict:
    """A ``tools/loadgen.py --out`` artifact (BENCH_SERVE_rNN.json);
    raises ValueError on anything else."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("bench") != "BENCH_SERVE":
        raise ValueError(
            f"{path}: not a BENCH_SERVE artifact (tools/loadgen.py --out)"
        )
    return data


def render_bench_serve(b: dict, base: dict | None = None) -> str:
    """The "Serving bench" section: offered vs scored QPS, per-class
    client latency, and typed shed counts from a loadgen artifact (with
    ``base``, side by side against the previous round's)."""
    L = ["## Serving bench (loadgen)", ""]
    rows = [
        ("offered QPS", "qps_target"),
        ("scored QPS", "qps_achieved"),
        ("requests sent", "requests_sent"),
        ("requests scored", "requests_scored"),
        ("unanswered", "unanswered"),
        ("wire", "wire"),
        ("sender processes", "processes"),
        ("connections", "connections"),
        ("client failovers", "client_failovers"),
        ("deadline (ms)", "deadline_ms"),
    ]
    if base is None:
        L += ["| metric | run |", "|---|---:|"]
        for label, key in rows:
            L.append(f"| {label} | {_fmt(b.get(key)) if not isinstance(b.get(key), str) else b[key]} |")
    else:
        L += ["| metric | base | run |", "|---|---:|---:|"]
        for label, key in rows:
            bv, rv = base.get(key), b.get(key)
            bs = bv if isinstance(bv, str) else _fmt(bv)
            rs = rv if isinstance(rv, str) else _fmt(rv)
            L.append(f"| {label} | {bs} | {rs} |")
    for klass, h in sorted((b.get("client_ms_by_class") or {}).items()):
        bh = ((base or {}).get("client_ms_by_class") or {}).get(klass) or {}
        vs = f" (base p99 {bh.get('p99')})" if bh else ""
        L.append(
            f"- class {klass!r}: client p50 {h.get('p50')}ms, "
            f"p99 {h.get('p99')}ms over {_fmt(h.get('count'))} scored{vs}"
        )
    codes = b.get("shed_codes") or {}
    if codes:
        L.append(
            "- typed sheds: "
            + ", ".join(f"{k}={v}" for k, v in sorted(codes.items()))
        )
    L.append("")
    return "\n".join(L)


def compare_bench_serve(run_b: dict, base_b: dict, threshold: float) -> list[str]:
    """Strict-gate regressions between two BENCH_SERVE artifacts: scored
    QPS down past the threshold, any per-class CLIENT p99 up past it,
    and unanswered requests where the base had none.  Client-side
    latency, not engine-side: the queueing a saturated data plane hides
    from engine histograms is exactly what the client clock sees."""
    regressions = []
    rq, bq = run_b.get("qps_achieved"), base_b.get("qps_achieved")
    if (
        isinstance(rq, (int, float))
        and isinstance(bq, (int, float))
        and bq > 0
        and rq < bq * (1 - threshold)
    ):
        regressions.append(
            f"serving scored QPS regressed {(bq - rq) / bq * 100:.1f}% "
            f"(> {threshold * 100:.0f}%): {bq} -> {rq}"
        )
    elif rq is None and isinstance(bq, (int, float)) and bq > 0:
        regressions.append(
            f"run bench has no qps_achieved (base scored {bq}) — "
            "loadgen died before writing results?"
        )
    for klass, bh in sorted((base_b.get("client_ms_by_class") or {}).items()):
        bp = (bh or {}).get("p99")
        rp = ((run_b.get("client_ms_by_class") or {}).get(klass) or {}).get("p99")
        if (
            isinstance(rp, (int, float))
            and isinstance(bp, (int, float))
            and bp > 0
            and rp > bp * (1 + threshold)
        ):
            regressions.append(
                f"serving bench class {klass!r} client p99 regressed "
                f"{(rp - bp) / bp * 100:.1f}% (> {threshold * 100:.0f}%): "
                f"{bp}ms -> {rp}ms"
            )
    if (run_b.get("unanswered") or 0) > (base_b.get("unanswered") or 0):
        regressions.append(
            f"serving bench unanswered requests: "
            f"{base_b.get('unanswered') or 0} -> {run_b.get('unanswered') or 0} "
            "(every admitted request must resolve to a score or a typed shed)"
        )
    return regressions


# -- training bench (bench.py artifacts) ----------------------------------


def load_bench_train(path: str) -> dict:
    """A ``python bench.py`` result (BENCH_rNN.json): either the raw
    result dict bench prints, or the CI wrapper ``{cmd, rc, parsed,
    tail}`` that captures it (``parsed`` when the JSON line survived,
    else re-parsed from the stdout ``tail``).  Raises ValueError when no
    bench result can be recovered."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "cmd" in data and "tail" in data:
        parsed = data.get("parsed")
        if not isinstance(parsed, dict):
            # Wrapper kept only a stdout tail; the result is the last
            # line that parses as a JSON object (bench prints it last).
            parsed = None
            for line in reversed((data.get("tail") or "").splitlines()):
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    try:
                        parsed = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    break
        if not isinstance(parsed, dict):
            raise ValueError(
                f"{path}: bench wrapper holds no parseable result line "
                "(stdout tail truncated mid-JSON?)"
            )
        data = parsed
    if not isinstance(data, dict) or "value" not in data:
        raise ValueError(f"{path}: not a bench.py result artifact")
    return data


def render_bench_tail(b: dict, base: dict | None = None) -> str:
    """The "Sparse-tail A/B" section: XLA vs Pallas one-pass tail at the
    scale rung — examples/sec and HBM bytes/example, measured (XLA cost
    model of the compiled step) against modeled (the hand roofline), per
    mode.  With ``base``, prior-round numbers ride alongside."""
    L = ["## Sparse-tail A/B (XLA vs Pallas)", ""]
    ab = b.get("tail_ab")
    if not isinstance(ab, dict):
        L.append(
            "_no `tail_ab` key in this bench artifact (pre-tail-A/B round)_"
        )
        L.append("")
        return "\n".join(L)
    batch = ab.get("batch")
    if batch:
        L.append(f"Batch {_fmt(batch)}, scale rung vocab "
                 f"{_fmt(b.get('scale_vocab_rows'))} rows.")
        L.append("")
    base_modes = ((base or {}).get("tail_ab") or {}).get("modes") or {}
    L += [
        "| tail | ex/s | bytes/ex (measured) | bytes/ex (modeled) | note |",
        "|---|---:|---:|---:|---|",
    ]
    for mode, e in sorted((ab.get("modes") or {}).items()):
        note = e.get("skipped") or e.get("error") or ""
        if e.get("b65536_value"):
            note = f"B=65536: {_fmt(e['b65536_value'])} ex/s"
        elif e.get("b65536_error"):
            note = f"B=65536 failed: {str(e['b65536_error'])[:60]}"
        bm = base_modes.get(mode) or {}
        if bm.get("value") is not None:
            note = (note + "; " if note else "") + f"base {_fmt(bm['value'])} ex/s"
        L.append(
            f"| {mode} | {_fmt(e.get('value'))} | "
            f"{_fmt(e.get('measured_bytes_per_example'))} | "
            f"{_fmt(e.get('modeled_bytes_per_example'))} | {note} |"
        )
    L.append("")
    return "\n".join(L)


def compare_bench_tail(run_b: dict, base_b: dict, threshold: float) -> list[str]:
    """Strict-gate regressions between two bench artifacts' tail A/B:
    per-mode tail throughput down past the threshold, measured
    bytes/example up past it, and a mode the base measured going dark
    (skipped or errored) in the run."""
    regressions = []
    run_modes = (run_b.get("tail_ab") or {}).get("modes") or {}
    base_modes = (base_b.get("tail_ab") or {}).get("modes") or {}
    for mode, bm in sorted(base_modes.items()):
        bv = bm.get("value")
        if not isinstance(bv, (int, float)) or bv <= 0:
            continue
        rm = run_modes.get(mode) or {}
        rv = rm.get("value")
        if not isinstance(rv, (int, float)):
            why = rm.get("skipped") or rm.get("error") or "mode absent from run"
            regressions.append(
                f"{mode} tail went dark (base {bv} ex/s): {why}"
            )
            continue
        if rv < bv * (1 - threshold):
            regressions.append(
                f"{mode} tail throughput regressed "
                f"{(bv - rv) / bv * 100:.1f}% (> {threshold * 100:.0f}%): "
                f"{bv} -> {rv} ex/s"
            )
        rb, bb = rm.get("measured_bytes_per_example"), bm.get(
            "measured_bytes_per_example"
        )
        if (
            isinstance(rb, (int, float))
            and isinstance(bb, (int, float))
            and bb > 0
            and rb > bb * (1 + threshold)
        ):
            regressions.append(
                f"{mode} tail measured bytes/example regressed "
                f"{(rb - bb) / bb * 100:.1f}% (> {threshold * 100:.0f}%): "
                f"{bb} -> {rb}"
            )
    return regressions


# -- static analysis ------------------------------------------------------


def load_analysis(path: str) -> dict:
    """Output of ``tools/analysis/run.py --json``; raises ValueError on
    anything else."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "counts" not in data or "baseline" not in data:
        raise ValueError(f"{path}: not an analysis JSON (run.py --json output)")
    return data


def render_analysis(a: dict, base_a: dict | None = None) -> str:
    """The "Analysis" section: findings by rule/severity + baseline debt,
    per rule.  Debt = findings the committed baseline excuses; the
    compare gate fails --strict when it grows.  With ``base_a`` (the
    --analysis-base payload) each rule row also shows its debt DELTA, so
    "who re-pinned instead of fixing" is visible per checker, not just in
    the total."""
    counts = a.get("counts", {})
    base = a.get("baseline", {})
    new = a.get("new", [])
    debt_by_rule = (base.get("debt_by_rule") or {})
    base_debt_by_rule = (
        ((base_a.get("baseline") or {}).get("debt_by_rule") or {})
        if base_a is not None
        else None
    )
    L = ["## Analysis (static invariant checkers)", ""]
    if base_debt_by_rule is None:
        L.append("| rule | findings | pinned debt |")
        L.append("|---|---:|---:|")
    else:
        L.append("| rule | findings | pinned debt | Δ debt vs base |")
        L.append("|---|---:|---:|---:|")
    rules = sorted(set(counts.get("by_rule") or {}) | set(debt_by_rule)
                   | set(base_debt_by_rule or {}))
    for rule in rules:
        n = (counts.get("by_rule") or {}).get(rule, 0)
        d = debt_by_rule.get(rule, 0)
        if base_debt_by_rule is None:
            L.append(f"| {rule} | {n} | {d} |")
        else:
            delta = d - base_debt_by_rule.get(rule, 0)
            L.append(f"| {rule} | {n} | {d} | {delta:+d} |")
    if not rules:
        L.append("| – | 0 | 0 |" if base_debt_by_rule is None else "| – | 0 | 0 | +0 |")
    if a.get("lock_drift"):
        L.append("")
        L.append(
            f"**LOCKFILE DRIFT: {a['lock_drift']} format-drift finding(s)** — "
            "a persisted/wire registry diverged from formats.lock.json "
            "(removal/reorder is never legal; additions need --write-lock "
            "in the same diff)."
        )
    # Blocking-under-lock hotspots: where the wedge-class debt lives,
    # pinned or not — the worklist for shrinking lock scopes (PR 15).
    blocking = [
        f
        for f in (a.get("findings") or [])
        if f.get("rule") == "blocking-under-lock"
    ]
    if blocking:
        per_path: dict[str, int] = {}
        for f in blocking:
            per_path[f.get("path", "?")] = per_path.get(f.get("path", "?"), 0) + 1
        L.append("")
        L.append("**Blocking-under-lock hotspots** (unbounded waits while a lock is held):")
        ranked = sorted(per_path.items(), key=lambda kv: (-kv[1], kv[0]))
        for path, n in ranked[:8]:
            L.append(f"- {path}: {n} site(s)")
        if len(ranked) > 8:
            L.append(f"- … and {len(ranked) - 8} more file(s)")
    sev = counts.get("by_severity") or {}
    L.append("")
    L.append(
        f"Severity: {sev.get('error', 0)} error(s), "
        f"{sev.get('warning', 0)} warning(s).  Baseline debt: "
        f"{base.get('debt', 0)} pinned finding(s)"
        + (f", {base.get('stale', 0)} stale pin(s) to prune" if base.get("stale") else "")
        + (
            f", {base.get('unjustified', 0)} pin(s) MISSING a justification"
            if base.get("unjustified")
            else ""
        )
        + "."
    )
    if new:
        L.append("")
        L.append(f"**{len(new)} NEW finding(s) (not in the baseline):**")
        for f in new[:20]:
            L.append(
                f"- `{f.get('rule')}` {f.get('path')}:{f.get('line')} — "
                f"{f.get('message')}"
            )
        if len(new) > 20:
            L.append(f"- … and {len(new) - 20} more")
    L.append("")
    return "\n".join(L)


def compare_analysis(run_a: dict, base_a: dict) -> list[str]:
    """Strict-gate regressions: baseline-debt growth (total and per
    rule), new findings, and PERSISTED-FORMAT LOCKFILE DRIFT.  (run.py
    --strict already fails on new findings in CI; this gate catches the
    debt creeping up between two otherwise-green runs — i.e. someone
    re-baselining instead of fixing — and drift someone pinned into the
    baseline to sneak past run.py.)"""
    regressions = []
    rd = (run_a.get("baseline") or {}).get("debt", 0) or 0
    bd = (base_a.get("baseline") or {}).get("debt", 0) or 0
    if rd > bd:
        rbr = (run_a.get("baseline") or {}).get("debt_by_rule") or {}
        bbr = (base_a.get("baseline") or {}).get("debt_by_rule") or {}
        grew = [
            f"{r} +{rbr.get(r, 0) - bbr.get(r, 0)}"
            for r in sorted(set(rbr) | set(bbr))
            if rbr.get(r, 0) > bbr.get(r, 0)
        ]
        regressions.append(
            f"analysis baseline debt grew: {bd} -> {rd} pinned finding(s) "
            f"({', '.join(grew) or 'total'}) — fix findings instead of "
            "re-pinning them"
        )
    rn, bn = len(run_a.get("new") or ()), len(base_a.get("new") or ())
    if rn > bn:
        regressions.append(f"new analysis findings: {bn} -> {rn}")
    drift = run_a.get("lock_drift", 0) or 0
    if drift:
        regressions.append(
            f"persisted-format lockfile drift: {drift} format-drift "
            "finding(s) — registries diverged from formats.lock.json "
            "(append-only; removal/reorder is never legal)"
        )
    return regressions


# -- bench wiring ---------------------------------------------------------


def write_bench_report(result: dict, root: str, prefix: str = "BENCH_r") -> str | None:
    """Delta table for one bench result vs the previous committed round:
    finds the highest-numbered ``BENCH_rNN.json`` under ``root``, compares
    every shared numeric key, and writes ``REPORT_rMM.md`` (MM = NN + 1,
    the round this result will be committed as) next to it.  Returns the
    report path, or None when there is no previous round to compare."""
    rounds = []
    for p in glob.glob(os.path.join(root, prefix + "*.json")):
        m = re.search(r"_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    if not rounds:
        return None
    prev_n, prev_path = max(rounds)
    try:
        with open(prev_path) as f:
            prev = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    L = [
        f"# Bench report — round r{prev_n + 1:02d} vs r{prev_n:02d}",
        "",
        f"Baseline file: `{os.path.basename(prev_path)}`.  Positive delta =",
        "this run is higher; whether that is good depends on the key",
        "(examples/sec up = good, *_error present = bad).",
        "",
        "| key | " + f"r{prev_n:02d} | new | delta |",
        "|---|---:|---:|---:|",
    ]
    keys = [
        k
        for k in result
        if isinstance(result.get(k), (int, float))
        and isinstance(prev.get(k), (int, float))
    ]
    for k in sorted(keys):
        a, b = result[k], prev[k]
        delta = f"{(a - b) / abs(b) * 100:+.1f}%" if b else f"{a - b:+g}"
        L.append(f"| {k} | {_fmt(b)} | {_fmt(a)} | {delta} |")
    only_new = sorted(set(result) - set(prev))
    only_old = sorted(set(prev) - set(result))
    if only_new:
        L += ["", "New keys: " + ", ".join(f"`{k}`" for k in only_new)]
    if only_old:
        L += ["", "Dropped keys: " + ", ".join(f"`{k}`" for k in only_old)]
    L.append("")
    out = os.path.join(root, f"REPORT_r{prev_n + 1:02d}.md")
    with open(out, "w") as f:
        f.write("\n".join(L))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="report",
        description="Render a fast_tffm_tpu telemetry JSONL run; "
        "--compare gates regressions (exit 1).",
    )
    ap.add_argument(
        "run",
        nargs="+",
        help="telemetry JSONL file(s); pass every per-host file of one "
        "multi-process run (RUN.jsonl RUN.p1.jsonl ...) to merge them "
        "into a single report with per-host columns",
    )
    ap.add_argument(
        "--compare",
        metavar="BASE",
        nargs="+",
        help="baseline telemetry JSONL file(s) to diff against (per-host "
        "files merge like the run's)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.15,
        help="max tolerated median-throughput drop vs BASE (fraction, default 0.15)",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="also fail on NEW steady-state compiles / stalls / anomalies / "
        "faults / restarts / rollbacks, and on freshness-p99 or "
        "measured-bytes-per-example regressions past --threshold",
    )
    ap.add_argument("--out", metavar="PATH", help="write the report here instead of stdout")
    ap.add_argument(
        "--analysis",
        metavar="JSON",
        help="static-analysis results (tools/analysis/run.py --json): "
        "render an Analysis section; with --compare --strict, gate on "
        "baseline-debt growth vs --analysis-base",
    )
    ap.add_argument(
        "--analysis-base",
        metavar="JSON",
        help="baseline run's analysis JSON for the debt-growth gate",
    )
    ap.add_argument(
        "--bench-serve",
        metavar="JSON",
        help="serving bench artifact (tools/loadgen.py --out, "
        "BENCH_SERVE_rNN.json): render a Serving bench section; with "
        "--strict and --bench-serve-base, gate on scored-QPS and "
        "per-class client-p99 regressions past --threshold",
    )
    ap.add_argument(
        "--bench-serve-base",
        metavar="JSON",
        help="baseline round's serving bench artifact for the QPS/p99 gate",
    )
    ap.add_argument(
        "--bench",
        metavar="JSON",
        help="training bench artifact (python bench.py output or the CI "
        "wrapper, BENCH_rNN.json): render a Sparse-tail A/B section "
        "(XLA vs Pallas tail, ex/s + bytes/example measured vs "
        "modeled); with --strict and --bench-base, gate on per-mode "
        "tail-throughput and bytes/example regressions past --threshold",
    )
    ap.add_argument(
        "--bench-base",
        metavar="JSON",
        help="baseline round's training bench artifact for the tail gate",
    )
    args = ap.parse_args(argv)

    def _load_many(paths):
        records = []
        for p in paths:
            records.extend(load_run(p))
        return records

    try:
        run = summarize(_load_many(args.run))
    except (OSError, ValueError) as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    title = ", ".join(os.path.basename(p) for p in args.run)
    text = render(run, title=title)
    rc = 0
    if args.analysis_base and not args.analysis:
        # A dropped --analysis must not silently skip the debt gate and
        # exit 0 — half a flag pair is a usage error, not a pass.
        print(
            "report: --analysis-base requires --analysis (the run's own "
            "analysis JSON) — debt gate would be silently skipped",
            file=sys.stderr,
        )
        return 2
    if args.bench_serve_base and not args.bench_serve:
        print(
            "report: --bench-serve-base requires --bench-serve (the run's "
            "own bench artifact) — QPS/p99 gate would be silently skipped",
            file=sys.stderr,
        )
        return 2
    if args.bench_base and not args.bench:
        print(
            "report: --bench-base requires --bench (the run's own bench "
            "artifact) — tail gate would be silently skipped",
            file=sys.stderr,
        )
        return 2
    bench_run = bench_base = None
    if args.bench_serve:
        try:
            bench_run = load_bench_serve(args.bench_serve)
            if args.bench_serve_base:
                bench_base = load_bench_serve(args.bench_serve_base)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"report: {e}", file=sys.stderr)
            return 2
        text = text + "\n" + render_bench_serve(bench_run, bench_base)
    train_run = train_base = None
    if args.bench:
        try:
            train_run = load_bench_train(args.bench)
            if args.bench_base:
                train_base = load_bench_train(args.bench_base)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"report: {e}", file=sys.stderr)
            return 2
        text = text + "\n" + render_bench_tail(train_run, train_base)
    run_analysis = base_analysis = None
    if args.analysis:
        try:
            run_analysis = load_analysis(args.analysis)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"report: {e}", file=sys.stderr)
            return 2
        if args.analysis_base:
            try:
                base_analysis = load_analysis(args.analysis_base)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"report: {e}", file=sys.stderr)
                return 2
        text = text + "\n" + render_analysis(run_analysis, base_analysis)
    if args.compare:
        try:
            base = summarize(_load_many(args.compare))
        except (OSError, ValueError) as e:
            print(f"report: {e}", file=sys.stderr)
            return 2
        cmp_text, regressions = compare(
            run, base, threshold=args.threshold, strict=args.strict
        )
        if args.strict and run_analysis is not None:
            if not args.analysis_base:
                print(
                    "report: note: --analysis given without "
                    "--analysis-base — debt-growth gate skipped",
                    file=sys.stderr,
                )
            else:
                extra = compare_analysis(run_analysis, base_analysis)
                if extra:
                    cmp_text += "**ANALYSIS REGRESSED:**\n" + "\n".join(
                        f"- {r}" for r in extra
                    ) + "\n"
                    regressions.extend(extra)
        text = text + "\n" + cmp_text
        if regressions:
            rc = 1
    # The serving-bench gate rides on --strict alone (no --compare
    # needed): CI keeps only the BENCH_SERVE artifacts between rounds,
    # not the raw telemetry JSONLs.
    if args.strict and bench_run is not None:
        if bench_base is None:
            print(
                "report: note: --bench-serve given without "
                "--bench-serve-base — serving bench gate skipped",
                file=sys.stderr,
            )
        else:
            extra = compare_bench_serve(bench_run, bench_base, args.threshold)
            if extra:
                text += (
                    "\n**SERVING BENCH REGRESSED:**\n"
                    + "\n".join(f"- {r}" for r in extra)
                    + "\n"
                )
                rc = 1
    # Same contract for the training-bench tail gate: --strict alone,
    # no --compare needed (only the BENCH_r artifacts persist in CI).
    if args.strict and train_run is not None:
        if train_base is None:
            print(
                "report: note: --bench given without --bench-base — "
                "sparse-tail gate skipped",
                file=sys.stderr,
            )
        else:
            extra = compare_bench_tail(train_run, train_base, args.threshold)
            if extra:
                text += (
                    "\n**SPARSE-TAIL BENCH REGRESSED:**\n"
                    + "\n".join(f"- {r}" for r in extra)
                    + "\n"
                )
                rc = 1
    if args.out:
        # tmp + os.replace, inline (this tool stays stdlib-only): a
        # regenerated report must never be readable half-written.
        tmp = f"{args.out}.{os.getpid():x}.tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, args.out)
        print(f"report -> {args.out}", file=sys.stderr)
    else:
        print(text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
