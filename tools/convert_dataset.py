#!/usr/bin/env python
"""Convert libsvm/libffm text files to the FMB packed binary format.

One FMB per source file (per-file example weights keep their alignment):

    python tools/convert_dataset.py data/train.libsvm data/test.libsvm \
        --vocabulary-size 1048576 [--hash-feature-id] [--max-nnz 39]

writes data/train.libsvm.fmb and data/test.libsvm.fmb.  Training/predict
then accept the .fmb paths directly in train_files/predict_files — or set
``binary_cache = true`` in [Train] and the conversion happens (and stays
fresh) automatically.

--inspect prints an existing FMB file's header instead of converting.
--stats additionally scans each output for wire compressibility: the
all-ones-vals fraction, the constant-fields fraction, and the projected
packed-wire byte saving (wire_format = packed elides what the v2 header
flags promise).  Inputs that already are FMB are scanned in place.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+", help="libsvm/libffm text files (or FMB with --inspect)")
    ap.add_argument("--vocabulary-size", type=int, default=1 << 20)
    ap.add_argument("--hash-feature-id", action="store_true")
    ap.add_argument("--max-nnz", type=int, default=0, help="stored width (default: file's widest row)")
    ap.add_argument("-o", "--output", nargs="*", default=None,
                    help="output paths (default: <file>.fmb), aligned with files")
    ap.add_argument("--inspect", action="store_true", help="print FMB headers and exit")
    ap.add_argument(
        "--stats",
        action="store_true",
        help="report per-file wire compressibility (all-ones-vals / "
        "constant-fields fractions, projected packed-wire saving)",
    )
    args = ap.parse_args()

    import json

    from fast_tffm_tpu.data.binary import fmb_stats, is_fmb, open_fmb, write_fmb

    if args.inspect:
        for path in args.files:
            f = open_fmb(path)
            print(
                f"{path}: rows={f.n_rows} width={f.width} "
                f"vocabulary_size={f.vocabulary_size} hashed={f.hashed} "
                f"ids={f.ids.dtype} flags={f.flags} "
                f"bytes={os.path.getsize(path)}"
            )
        return

    outs = args.output if args.output else [p + ".fmb" for p in args.files]
    if len(outs) != len(args.files):
        ap.error(f"{len(outs)} outputs for {len(args.files)} inputs")
    for src, dst in zip(args.files, outs):
        if args.stats and is_fmb(src):
            # Already converted: scan in place, no rebuild.
            print(json.dumps(fmb_stats(src)))
            continue
        t0 = time.perf_counter()
        write_fmb(
            src,
            dst,
            vocabulary_size=args.vocabulary_size,
            hash_feature_id=args.hash_feature_id,
            max_nnz=args.max_nnz or None,
        )
        f = open_fmb(dst)
        dt = time.perf_counter() - t0
        print(
            f"{src} -> {dst}: {f.n_rows} rows, width {f.width}, "
            f"{os.path.getsize(dst)} bytes in {dt:.1f}s "
            f"({f.n_rows / max(dt, 1e-9):,.0f} rows/s)"
        )
        if args.stats:
            print(json.dumps(fmb_stats(dst)))


if __name__ == "__main__":
    main()
