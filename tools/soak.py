#!/usr/bin/env python
"""Sustained online-learning soak: trainer + publisher + loaded fleet, with chaos.

The production soak test ROADMAP item 4 names: every piece of the online
loop exists (delta publish, replicated serving with exactly-once reload
fan-out, freshness SLOs, supervised restart), but nothing had ever run
them CONCURRENTLY for a sustained window under load and live faults.
This harness does, end to end, with only repo machinery:

  * an event WRITER appends rows to an FMS stream at an open-loop rate,
    executing the stream-tier FaultPlan kinds (``stream_stall@N`` — the
    writer goes silent N seconds; ``append_torn@K`` — the Kth append
    leaves a torn trailing record for a while);
  * the ONLINE TRAINER (``fast_tffm.py train --supervised --resume``)
    tail-follows the stream with ``delta_every_steps`` publishing
    continuously and async full saves; its FaultPlan SIGKILLs it
    mid-run (supervised restart + exact mid-stream resume) and tears a
    delta write (chain repair);
  * a SERVING FLEET (``serve --port`` → router + N replica workers)
    hot-applies the delta chain while an open-loop load client scores
    against it; full mode SIGKILLs one replica mid-traffic (failover)
    — every admitted request must still get exactly one response;
  * the SENTINEL loop polls the ``stats`` wire op and the checkpoint
    chain every tick and emits one ``kind=soak`` record per tick:
    trainer alive (or cleanly restarting), zero unanswered requests so
    far, fleet freshness within the SLO envelope, delta chain length
    and on-disk footprint bounded (the age/size compaction invariant),
    zero steady-state recompiles on every replica.

Writes PROBE_SOAK JSON (the committed artifact) and exits nonzero if
any sentinel failed.  ``--smoke`` is the ~30 s miniature wired into
tier-1 (1 replica, 1 trainer kill + stream stall, all sentinels live);
the full run is ``--minutes 10`` (slow, the committed probe).

Usage:
    python tools/soak.py --minutes 10 --replicas 2 --qps 250
    python tools/soak.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fast_tffm_tpu.telemetry import arm_hang_exit  # noqa: E402

import numpy as np  # noqa: E402

VOCAB = 1 << 12
WIDTH = 6
K = 4


def _synth_batch(rng, rows: int):
    """One append's worth of synthetic rows (mixed nnz 1..WIDTH so the
    serving ladder and the trainer see every width)."""
    nnz = rng.integers(1, WIDTH + 1, size=rows)
    ids = np.zeros((rows, WIDTH), np.int64)
    vals = np.zeros((rows, WIDTH), np.float32)
    for r in range(rows):
        k = int(nnz[r])
        ids[r, :k] = rng.choice(VOCAB, size=k, replace=False)
        vals[r, :k] = np.round(np.abs(rng.normal(size=k)) + 0.1, 4)
    labels = rng.integers(0, 2, size=rows)
    return labels, ids, vals, nnz


def _score_lines(rng, n: int) -> list[str]:
    out = []
    for _ in range(n):
        k = int(rng.integers(1, WIDTH + 1))
        ids = rng.choice(VOCAB, size=k, replace=False)
        vals = np.round(np.abs(rng.normal(size=k)) + 0.1, 4)
        out.append(
            f"{int(rng.integers(0, 2))} "
            + " ".join(f"{i}:{v}" for i, v in zip(ids, vals))
        )
    return out


def _train_cfg(d: str, run_id: str, a) -> str:
    path = os.path.join(d, "train.cfg")
    with open(path, "w") as f:
        f.write(
            f"""
[General]
model = fm
factor_num = {K}
vocabulary_size = {VOCAB}
model_file = {d}/m.ckpt

[Train]
train_files = {d}/stream.fms
max_nnz = {WIDTH}
batch_size = {a.batch_size}
epoch_num = 1
learning_rate = 0.05
log_every = {a.log_every}
metrics_path = {d}/trainer.jsonl

[Online]
follow = true
poll_s = 0.05
idle_timeout_s = {a.idle_timeout_s}
adagrad_decay = {a.decay}

[Checkpoint]
async_save = true
delta_every_steps = {a.delta_every_steps}
delta_chain_max = {a.chain_max}
full_every_s = {a.full_every_s}

[Telemetry]
run_id = {run_id}
stall_timeout_s = {a.stall_timeout_s}

[Resilience]
restart_max = 6
restart_backoff_s = 0.2
restart_backoff_max_s = 2.0
"""
        )
    return path


def _serve_cfg(d: str, run_id: str, a) -> str:
    path = os.path.join(d, "serve.cfg")
    with open(path, "w") as f:
        f.write(
            f"""
[General]
model = fm
factor_num = {K}
vocabulary_size = {VOCAB}
model_file = {d}/m.ckpt

[Train]
max_nnz = {WIDTH}
metrics_path = {d}/serve.jsonl

[Telemetry]
run_id = {run_id}

[Serving]
buckets = 1 8 64
flush_deadline_ms = 3
replicas = {a.replicas}
reload_interval_s = {a.reload_interval_s}
deadline_ms = {a.deadline_ms}
"""
        )
    return path


def _seed_checkpoint(d: str, labels, ids, vals) -> None:
    """Pre-train a few batches so the fleet has a model to load before
    the online trainer's first publish."""
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.training import train

    seed_file = os.path.join(d, "seed.libsvm")
    with open(seed_file, "w") as f:
        for r in range(ids.shape[0]):
            toks = " ".join(
                f"{ids[r, c]}:{vals[r, c]:.4f}"
                for c in range(ids.shape[1])
                if vals[r, c] != 0
            )
            f.write(f"{labels[r]} {toks}\n")
    cfg = Config(
        model="fm", factor_num=K, vocabulary_size=VOCAB, max_nnz=WIDTH,
        model_file=os.path.join(d, "m.ckpt"), train_files=(seed_file,),
        epoch_num=1, batch_size=256, learning_rate=0.05, log_every=1000,
    ).validate()
    train(cfg, log=lambda *_: None)


class Writer(threading.Thread):
    """Open-loop event writer: appends ``rows`` every ``interval`` s,
    executing the stream-tier fault schedule."""

    def __init__(self, stream_path, a, stream_faults, log):
        super().__init__(name="soak-writer", daemon=True)
        from fast_tffm_tpu.data.stream import StreamWriter

        self.w = StreamWriter(stream_path, width=WIDTH, vocabulary_size=VOCAB)
        self.rows = a.append_rows
        self.interval = a.append_interval_s
        self.stop = threading.Event()
        self.rng = np.random.default_rng(1234)
        self.appended_rows = 0
        self.stalls_done = 0
        self.torn_done = 0
        self.stalls_planned = [
            e["at"] for e in stream_faults if e["kind"] == "stream_stall"
        ]
        self.torn_planned = {
            e["at"] for e in stream_faults if e["kind"] == "append_torn"
        }
        self._stall_at: dict[int, int] = {}  # append ordinal -> pause s
        self.total_appends_hint = 0
        self._log = log

    def run(self):
        # Spread the planned stalls over the run's middle: stall i of S
        # fires after append ~hint·(i+1)/(S+1) — EVERY planned stall
        # executes (the final gate compares executed vs planned), with
        # none so early the loop hasn't warmed or so late the drain eats
        # it.  (The @N value is the pause LENGTH in seconds, not a
        # position — documented in resilience.STREAM_FAULT_KINDS.)
        hint = max(4, self.total_appends_hint)
        for i, pause in enumerate(self.stalls_planned):
            at = max(2, hint * (i + 1) // (len(self.stalls_planned) + 1))
            while at in self._stall_at:  # distinct ordinals
                at += 1
            self._stall_at[at] = pause
        n = 0
        while not self.stop.is_set():
            labels, ids, vals, nnz = _synth_batch(self.rng, self.rows)
            n += 1
            if n in self.torn_planned:
                # append_torn@K: flush a PARTIAL trailing record, hold it
                # torn for a couple of poll intervals, then complete it —
                # the follow reader must wait it out, never parse it.
                self._log(f"soak-writer: torn append #{n} (held 0.6s)")
                self.w.append_torn(labels, ids, vals, nnz=nnz)
                time.sleep(0.6)
                self.w.complete_torn()
                self.torn_done += 1
            else:
                self.w.append(labels, ids, vals, nnz=nnz)
            self.appended_rows += self.rows * 1
            if n in self._stall_at:
                pause = self._stall_at.pop(n)
                self._log(f"soak-writer: stream stall {pause}s (writer silent)")
                if self.stop.wait(pause):
                    break
                self.stalls_done += 1
            if self.stop.wait(self.interval):
                break
        self.w.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--smoke", action="store_true",
                    help="~30s miniature: 1 replica, trainer kill + stream "
                    "stall, every sentinel live (the tier-1 smoke)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--qps", type=float, default=250.0)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--append-rows", type=int, default=512)
    ap.add_argument("--append-interval-s", type=float, default=0.25)
    ap.add_argument("--delta-every-steps", type=int, default=20)
    ap.add_argument("--chain-max", type=int, default=12)
    ap.add_argument("--full-every-s", type=float, default=45.0)
    ap.add_argument("--reload-interval-s", type=float, default=0.25)
    ap.add_argument("--deadline-ms", type=float, default=200.0)
    ap.add_argument("--decay", type=float, default=0.999)
    ap.add_argument("--log-every", type=int, default=50)
    ap.add_argument("--stall-timeout-s", type=float, default=2.0)
    ap.add_argument("--idle-timeout-s", type=float, default=12.0)
    ap.add_argument("--freshness-p99-budget-ms", type=float, default=2000.0,
                    help="fleet publish->first-scored p99 envelope (the "
                    "PR-9 probe measured ~343ms at light load; the budget "
                    "leaves headroom for a loaded CPU box)")
    ap.add_argument("--disk-budget-mb", type=float, default=256.0)
    ap.add_argument("--fault-plan", default=None,
                    help="override the trainer+stream fault schedule "
                    "(default depends on --smoke)")
    ap.add_argument("--keep-dir", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    if args.smoke:
        args.minutes = min(args.minutes, 0.45)
        args.replicas = 1
        args.qps = 80.0
        args.append_interval_s = 0.15
        args.delta_every_steps = 8
        args.chain_max = 8
        args.full_every_s = 8.0
        args.idle_timeout_s = 8.0
        args.stall_timeout_s = 1.0
        fault_plan = args.fault_plan or "kill@40,stream_stall@2"
    else:
        fault_plan = args.fault_plan or (
            "kill@400,torn_delta@3,replica_kill@1,stream_stall@4,append_torn@5"
        )
    out_path = args.out or os.path.join(
        REPO, "PROBE_SOAK_r11.json" if not args.smoke else "PROBE_SOAK_smoke.json"
    )
    hang_timer = arm_hang_exit(max(240.0, args.minutes * 60 * 3), what="soak")

    import tempfile

    from fast_tffm_tpu.checkpoint import read_delta_chain
    from fast_tffm_tpu.resilience import FaultPlan
    from fast_tffm_tpu.serving.client import ServeConnection, spawn_serve
    from fast_tffm_tpu.telemetry import (
        RunMonitor,
        artifact_stamp,
        new_run_id,
        write_json_artifact,
    )

    plan = FaultPlan.parse(fault_plan)
    stream_faults = plan.stream_events()
    trainer_fault_spec = ",".join(
        f"{e['kind']}@{e['at']}" + (f":{e['until']}" if "until" in e else "")
        for e in plan.events
        if e["kind"] not in ("stream_stall", "append_torn", "replica_kill",
                             "replica_slow", "reload_corrupt")
    )
    replica_kills = [e for e in plan.events if e["kind"] == "replica_kill"]

    run_id = new_run_id()
    tmp_ctx = None
    if args.keep_dir:
        os.makedirs(args.keep_dir, exist_ok=True)
        d = args.keep_dir
    else:
        tmp_ctx = tempfile.TemporaryDirectory()
        d = tmp_ctx.name
    log = lambda *a_: print("soak:", *a_, flush=True)
    soak_jsonl = os.path.join(d, "soak.jsonl")
    monitor = RunMonitor(soak_jsonl, run_id=run_id, source="train")
    ticks: list[dict] = []
    t_start = time.monotonic()

    def tick_record(phase: str, checks: dict, extra: dict | None = None):
        ok = all(bool(v) for v in checks.values())
        rec = {
            "phase": phase,
            "elapsed_s": round(time.monotonic() - t_start, 1),
            "ok": ok,
            **{f"check_{k}": bool(v) for k, v in checks.items()},
            **(extra or {}),
        }
        ticks.append(rec)
        try:
            monitor.emit("soak", step=len(ticks), **rec)
        except (OSError, ValueError):
            pass  # lost soak record; the tick verdict is in `ticks` either way
        log(
            f"[{rec['elapsed_s']:7.1f}s] {phase}: "
            + ("OK" if ok else "FAIL " + str([k for k, v in checks.items() if not v]))
        )
        return ok

    serve_proc = None
    trainer = None
    writer = None
    clients: list[ServeConnection] = []
    try:
        # -- bring-up ----------------------------------------------------
        rng = np.random.default_rng(77)
        labels, ids, vals, _ = _synth_batch(rng, 1024)
        _seed_checkpoint(d, labels, ids, vals)
        log("seed checkpoint written")

        stream_path = os.path.join(d, "stream.fms")
        writer = Writer(stream_path, args, stream_faults, log)
        total_s = args.minutes * 60.0
        writer.total_appends_hint = max(4, int(total_s / args.append_interval_s))
        # Warm prefix so the trainer has data the moment it starts.
        for _ in range(3):
            lb, id_, vl, nz = _synth_batch(writer.rng, args.append_rows)
            writer.w.append(lb, id_, vl, nnz=nz)
            writer.appended_rows += args.append_rows
        writer.start()

        tcfg = _train_cfg(d, run_id, args)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        tcmd = [
            sys.executable, os.path.join(REPO, "fast_tffm.py"), "train", tcfg,
            "--supervised", "--resume",
        ]
        if trainer_fault_spec:
            tcmd += ["--fault-plan", trainer_fault_spec]
        trainer_log = open(os.path.join(d, "trainer.log"), "w")
        trainer = subprocess.Popen(
            tcmd, stdout=trainer_log, stderr=subprocess.STDOUT, env=env, cwd=REPO
        )
        log(f"trainer (supervised) pid {trainer.pid}: {' '.join(tcmd[2:])}")

        scfg = _serve_cfg(d, run_id, args)
        serve_proc, port = spawn_serve(scfg, port=0, timeout_s=600.0)
        log(f"serving fleet up on port {port} ({args.replicas} replica(s))")
        control = ServeConnection(port)
        clients.append(control)

        # -- load client (open loop) ------------------------------------
        sent = [0]
        answered = [0]
        codes: dict[str, int] = {}
        lat: list[float] = []
        lat_lock = threading.Lock()

        def on_response(msg, meta):
            answered[0] += 1
            if meta is not None:
                with lat_lock:
                    lat.append(time.perf_counter() - meta)
            if "score" not in msg:
                code = str(msg.get("code") or "error")
                with lat_lock:
                    codes[code] = codes.get(code, 0) + 1
            return True

        data = ServeConnection(port, on_response=on_response)
        clients.append(data)
        stop_load = threading.Event()

        def load_loop():
            lrng = np.random.default_rng(9)
            lines = _score_lines(lrng, 2048)
            interval = 1.0 / args.qps
            t_next = time.perf_counter()
            i = 0
            while not stop_load.is_set():
                now = time.perf_counter()
                if now < t_next:
                    time.sleep(min(t_next - now, 0.05))
                    continue
                t_next += interval
                try:
                    data.send({"line": lines[i % len(lines)]}, meta=now)
                    sent[0] += 1
                except OSError:
                    break
                i += 1

        load_thread = threading.Thread(target=load_loop, name="soak-load", daemon=True)
        load_thread.start()

        # -- replica-kill schedule (full mode) ---------------------------
        kill_at = []
        if replica_kills and args.replicas > 1:
            for j, e in enumerate(replica_kills):
                kill_at.append(
                    (t_start + total_s * (0.35 + 0.3 * j), int(e["at"]) % args.replicas)
                )

        # -- sentinel loop ----------------------------------------------
        tick_s = 5.0 if args.smoke else 15.0
        end_t = t_start + total_s
        failures = 0
        max_chain = 0
        max_disk = 0
        chain_read_errors = 0
        chain_errors_streak = 0
        while time.monotonic() < end_t:
            time.sleep(tick_s)
            for when, victim in list(kill_at):
                if time.monotonic() >= when:
                    kill_at.remove((when, victim))
                    try:
                        stats0 = control.request({"op": "stats"}, timeout=30)
                        pid = next(
                            (
                                r["pid"]
                                for r in stats0.get("replicas", [])
                                if r.get("replica") == victim and r.get("pid")
                            ),
                            None,
                        )
                        if pid is None:
                            pid = (
                                stats0.get("engines", {})
                                .get(str(victim), {})
                                .get("pid")
                            )
                        if pid:
                            log(f"CHAOS: SIGKILL replica {victim} (pid {pid})")
                            os.kill(int(pid), signal.SIGKILL)
                    except Exception as e:
                        log(f"replica kill failed: {e!r}")
            try:
                stats = control.request({"op": "stats"}, timeout=30)
            except Exception as e:
                stats = {"error": repr(e)}
            # Chain + disk bounds (the compaction invariant).
            model_file = os.path.join(d, "m.ckpt")
            try:
                _, chain = read_delta_chain(model_file)
                chain_len = len(chain)
                chain_errors_streak = 0
            except Exception:
                # A torn delta (the injected fault) legitimately breaks the
                # chain READ until the next full save heals it (promote/
                # unlink) or the supervisor's repair quarantines the tail —
                # a transient, not a sentinel failure.  Persisting across
                # consecutive ticks IS one: compaction stopped working.
                chain_len = None
                chain_read_errors += 1
                chain_errors_streak += 1
            disk = 0
            for fn in os.listdir(d):
                if fn.startswith("m.ckpt"):
                    try:
                        disk += os.path.getsize(os.path.join(d, fn))
                    except OSError:
                        pass
            if chain_len is not None:
                max_chain = max(max_chain, chain_len)
            max_disk = max(max_disk, disk)
            scored_p99 = (stats.get("freshness") or {}).get(
                "scored_p99_ms_worst_replica"
            )
            staged = ((stats.get("freshness") or {}).get("staged_ms") or {})
            steady = [
                (e.get("steady_compiles"))
                for e in (stats.get("engines") or {}).values()
                if isinstance(e, dict) and "steady_compiles" in e
            ]
            unanswered_now = sent[0] - answered[0]
            checks = {
                "trainer_alive": trainer.poll() is None,
                "serving_alive": serve_proc.poll() is None,
                # In-flight backlog bounded: everything but the last few
                # seconds' sends must be answered (typed errors count —
                # unanswered means NO response line at all).
                "no_unanswered_backlog": unanswered_now <= max(64, args.qps * 3),
                "chain_bounded": (
                    chain_errors_streak < 3
                    if chain_len is None
                    else 0 <= chain_len <= args.chain_max
                ),
                "disk_bounded": disk <= args.disk_budget_mb * (1 << 20),
                "replicas_no_steady_recompiles": all((x or 0) == 0 for x in steady),
                "freshness_within_budget": (
                    scored_p99 is None
                    or scored_p99 <= args.freshness_p99_budget_ms
                ),
            }
            ok = tick_record(
                "steady",
                checks,
                {
                    "sent": sent[0],
                    "answered": answered[0],
                    "unanswered_now": unanswered_now,
                    "chain_len": chain_len,
                    "disk_bytes": disk,
                    "freshness_scored_p99_ms": scored_p99,
                    "freshness_staged_p99_ms": staged.get("p99"),
                    "reload_fanouts": stats.get("reload_fanouts"),
                    "failovers": stats.get("failovers"),
                    "appended_rows": writer.appended_rows,
                },
            )
            failures += 0 if ok else 1

        # -- drain -------------------------------------------------------
        stop_load.set()
        load_thread.join(timeout=10)
        writer.stop.set()
        writer.join(timeout=15)
        left = data.drain_inflight(timeout=30.0)
        unanswered = left  # no response line AT ALL after the drain window
        # Trainer: the writer stopped, so the follow stream idles out and
        # the trainer exits cleanly (final sync save) via its supervisor.
        trainer_rc = None
        try:
            trainer_rc = trainer.wait(timeout=args.idle_timeout_s * 3 + 60)
        except subprocess.TimeoutExpired:
            trainer.terminate()
        final_stats = {}
        try:
            final_stats = control.request({"op": "stats"}, timeout=30)
        except Exception as e:
            log(f"final stats poll failed (fleet already torn down?): {e!r}")

        # Trainer-side telemetry digest (restarts, stalls, ckpt counters,
        # steady compiles) from its JSONL.
        t_restarts = t_stream_idle_stalls = t_steady_compiles = 0
        t_ckpt = {}
        try:
            for line in open(os.path.join(d, "trainer.jsonl")):
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                k = r.get("kind")
                if k == "restart":
                    t_restarts += 1
                elif k == "stall" and "stream-idle" in str(r.get("classification")):
                    t_stream_idle_stalls += 1
                elif k == "compile" and not r.get("warmup"):
                    t_steady_compiles += r.get("compiles") or 0
                elif k == "summary":
                    t_ckpt = {
                        key: r[key]
                        for key in r
                        if key.startswith("ckpt_") or key.startswith("fault_")
                    }
        except OSError:
            pass

        planned_kills = sum(1 for e in plan.events if e["kind"] == "kill")
        gates = {
            "zero_unanswered": unanswered == 0,
            "all_sentinel_ticks_ok": failures == 0,
            "trainer_finished_cleanly": trainer_rc == 0,
            "trainer_restart_observed": t_restarts >= min(1, planned_kills),
            "trainer_zero_steady_recompiles": t_steady_compiles == 0,
            "chain_bounded_throughout": 0 <= max_chain <= args.chain_max,
            "disk_bounded_throughout": max_disk <= args.disk_budget_mb * (1 << 20),
            # The planned stream faults ACTUALLY executed (a schedule
            # that silently half-ran would report coverage it never had).
            "stream_faults_executed": (
                writer.stalls_done >= len(writer.stalls_planned)
                and writer.torn_done >= len(writer.torn_planned)
            ),
        }
        ok = tick_record(
            "final",
            gates,
            {
                "sent": sent[0],
                "answered": answered[0],
                "unanswered": unanswered,
                "trainer_rc": trainer_rc,
                "trainer_restarts": t_restarts,
                "stream_idle_stalls": t_stream_idle_stalls,
            },
        )

        with lat_lock:
            lats = sorted(lat)
        pct = lambda q: (
            round(lats[min(len(lats) - 1, int(q * len(lats)))] * 1e3, 2)
            if lats
            else None
        )
        result = {
            **artifact_stamp(run_id),
            "tool": "soak",
            "mode": "smoke" if args.smoke else "full",
            "duration_s": round(time.monotonic() - t_start, 1),
            "replicas": args.replicas,
            "qps_offered": args.qps,
            "fault_plan": plan.to_json(),
            "requests_sent": sent[0],
            "requests_answered": answered[0],
            "unanswered": unanswered,
            "typed_codes": codes,
            "client_latency_ms": {"p50": pct(0.5), "p99": pct(0.99)},
            "appended_rows": writer.appended_rows,
            "stream_stalls_executed": writer.stalls_done,
            "torn_appends_executed": writer.torn_done,
            "trainer_rc": trainer_rc,
            "trainer_restarts": t_restarts,
            "trainer_stream_idle_stalls": t_stream_idle_stalls,
            "trainer_steady_compiles": t_steady_compiles,
            "trainer_ckpt": t_ckpt,
            "max_chain_len": max_chain,
            "max_disk_bytes": max_disk,
            "chain_read_errors": chain_read_errors,
            "freshness_final": (final_stats.get("freshness") or {}),
            "router_failovers": final_stats.get("failovers"),
            "router_reload_fanouts": final_stats.get("reload_fanouts"),
            "sentinel_ticks": len(ticks),
            "sentinel_failures": failures + (0 if ok else 1),
            "gates": gates,
            "gate": "OK" if ok and failures == 0 else "REGRESSED",
            "ticks": ticks[-50:],
        }
        write_json_artifact(out_path, result, sort_keys=False)
        log(f"wrote {out_path} (gate: {result['gate']})")
        return 0 if result["gate"] == "OK" else 1
    finally:
        hang_timer.cancel()
        for c in clients:
            c.close()
        if serve_proc is not None and serve_proc.poll() is None:
            serve_proc.terminate()
            try:
                serve_proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                serve_proc.kill()
        if trainer is not None and trainer.poll() is None:
            trainer.terminate()
            try:
                trainer.wait(timeout=20)
            except subprocess.TimeoutExpired:
                trainer.kill()
        if writer is not None:
            writer.stop.set()
        monitor.close()
        if tmp_ctx is not None:
            tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
