#!/usr/bin/env python
"""Rolling quality backtest: replay a drifting day, online vs batch-retrain.

The online-learning quality question ROADMAP item 4 asks: does a trainer
that tail-follows the event stream (``[Online] follow = true``) actually
TRACK a moving distribution, or does it silently decay relative to the
"just retrain from scratch" reference?  This tool answers it with the
machinery the repo already trusts:

  * a synthetic DAY of timestamped events whose planted FM model DRIFTS
    hour by hour (a rotation between two planted parameter sets — the
    gen_synthetic planted-model idiom, made time-varying);
  * the ONLINE trainer consumes the day as a real append-only FMS stream
    through the real driver: hour h's rows are APPENDED, then the trainer
    ``--resume``s and follows until its max_batches bound — every hour
    boundary exercises the exact-position mid-stream cursor for real;
  * the BATCH reference retrains from scratch each hour on all data so
    far (the expensive thing production cannot afford to do hourly —
    that cost asymmetry is the point of the comparison);
  * after each hour both models score the NEXT hour's held-out rows
    (prequential evaluation) and one ``kind=quality`` record lands in
    the online run's telemetry JSONL: (hour, auc_online, auc_batch).

``tools/report.py ONLINE.jsonl --compare BATCH.jsonl --strict`` then
renders the AUC-by-hour table and gates on the worst-hour gap; this tool
runs that comparison itself, writes the committed artifact
(BACKTEST_r11.json), and exits nonzero if the online trainer trails the
batch reference by more than ``--threshold`` AUC at any hour.

Usage:
    python tools/backtest.py [--hours 24] [--rows-per-hour 4096] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fast_tffm_tpu.telemetry import arm_hang_exit

_HANG_TIMER = arm_hang_exit(3600, what="backtest")

import numpy as np  # noqa: E402

from gen_synthetic import _id_normal, _zipf_ids, planted_score  # noqa: E402

VOCAB = 1 << 12
FIELDS = 8
K = 4
SPREAD = 2.2  # label-noise calibration (quality_zoo rationale)


def _draw_rows(rng, rows: int):
    bounds = np.linspace(0, VOCAB, FIELDS + 1).astype(np.int64)
    ids = np.stack(
        [_zipf_ids(rng, rows, bounds[f], bounds[f + 1]) for f in range(FIELDS)],
        axis=1,
    )
    vals = np.round(
        np.abs(rng.normal(0.5, 0.35, size=(rows, FIELDS))) + 0.05, 4
    ).astype(np.float32)
    return ids.astype(np.int64), vals


def drifted_score(ids, vals, hour: int, hours: int):
    """Planted score that ROTATES between two planted FMs over the day:
    s_h = cos(θ_h)·s_A + sin(θ_h)·s_B, θ sweeping 60° — gradual concept
    drift, the regime online learning exists for.  Pure function of
    (ids, vals, hour), so train and held-out splits share the hour's
    model exactly (the _id_normal determinism rule)."""
    theta = (hour / max(1, hours - 1)) * (np.pi / 3.0)
    s_a = planted_score(ids, vals, factor_num=K, model_seed=4242)
    s_b = planted_score(ids, vals, factor_num=K, model_seed=8383)
    return np.cos(theta) * s_a + np.sin(theta) * s_b


def _labels(rng, score):
    s = (score - score.mean()) / (score.std() + 1e-6) * SPREAD
    return (rng.random(s.shape[0]) < 1.0 / (1.0 + np.exp(-s))).astype(np.int64)


def _write_libsvm(path, labels, ids, vals):
    with open(path, "w") as f:
        for r in range(ids.shape[0]):
            toks = " ".join(
                f"{ids[r, c]}:{vals[r, c]:.4f}" for c in range(ids.shape[1])
            )
            f.write(f"{labels[r]} {toks}\n")


def _gen_hour(hour: int, hours: int, rows: int, seed: int):
    rng = np.random.default_rng((seed, hour))
    ids, vals = _draw_rows(rng, rows)
    labels = _labels(rng, drifted_score(ids, vals, hour, hours))
    return labels, ids, vals


def _auc_on(cfg, heldout_file: str, max_nnz: int) -> float:
    """Held-out AUC of cfg.model_file's CURRENT checkpoint on one file,
    through the real restore + predict-step + streaming-AUC path."""
    import jax

    from fast_tffm_tpu.checkpoint import restore_checkpoint
    from fast_tffm_tpu.config import build_model
    from fast_tffm_tpu.trainer import init_state, make_predict_step
    from fast_tffm_tpu.training import _evaluate

    model = build_model(cfg)
    state = restore_checkpoint(
        cfg.model_file,
        init_state(model, jax.random.key(0), cfg.init_accumulator_value),
    )
    return _evaluate(
        cfg, make_predict_step(model), state, (heldout_file,), max_nnz
    )


def main(argv=None) -> int:
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.data.stream import StreamWriter
    from fast_tffm_tpu.telemetry import RunMonitor, new_run_id
    from fast_tffm_tpu.training import train

    ap = argparse.ArgumentParser()
    ap.add_argument("--hours", type=int, default=24, help="replayed 'day' length")
    ap.add_argument("--rows-per-hour", type=int, default=4096)
    ap.add_argument("--heldout-rows", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--decay", type=float, default=1.0,
                    help="[Online] adagrad_decay for the online trainer")
    ap.add_argument("--batch-epochs", type=int, default=1,
                    help="epochs per batch-retrain reference run")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="max tolerated (batch - online) held-out AUC gap")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--quick", action="store_true",
                    help="tiny smoke shapes (3 hours)")
    ap.add_argument("--keep-dir", default=None,
                    help="work in this dir (kept) instead of a tempdir")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BACKTEST_r11.json"))
    args = ap.parse_args(argv)
    if args.quick:
        args.hours, args.rows_per_hour, args.heldout_rows = 3, 1024, 512
        args.batch_size = 256

    # Batch-align every hour: the follow stream only emits FULL batches
    # (data/stream.py's exactness rule), so hour boundaries must land on
    # batch boundaries for the per-hour max_batches bound to be exact.
    args.rows_per_hour -= args.rows_per_hour % args.batch_size
    assert args.rows_per_hour > 0
    batches_per_hour = args.rows_per_hour // args.batch_size

    run_id = new_run_id()
    tmp_ctx = None
    if args.keep_dir:
        os.makedirs(args.keep_dir, exist_ok=True)
        tmp = args.keep_dir
    else:
        tmp_ctx = tempfile.TemporaryDirectory()
        tmp = tmp_ctx.name
    try:
        stream_path = os.path.join(tmp, "day.fms")
        writer = StreamWriter(
            stream_path, width=FIELDS, vocabulary_size=VOCAB
        )
        online_jsonl = os.path.join(tmp, "online.jsonl")
        batch_jsonl = os.path.join(tmp, "batch.jsonl")

        def online_cfg(max_batches: int) -> Config:
            return Config(
                model="fm", factor_num=K, vocabulary_size=VOCAB,
                model_file=os.path.join(tmp, "m_online.npz"),
                train_files=(stream_path,),
                epoch_num=1, batch_size=args.batch_size, max_nnz=FIELDS,
                # Low enough that ONE hour's handful of batches still
                # emits a kind=train record (the throughput gate reads
                # them; an hour is only a few batches at these shapes).
                learning_rate=args.lr, log_every=4,
                online_follow=True, online_max_batches=max_batches,
                online_poll_s=0.05, online_idle_timeout_s=30.0,
                online_adagrad_decay=args.decay,
                metrics_path=online_jsonl, telemetry_run_id=run_id,
            ).validate()

        def batch_cfg(hour_files) -> Config:
            return Config(
                model="fm", factor_num=K, vocabulary_size=VOCAB,
                model_file=os.path.join(tmp, "m_batch.npz"),
                train_files=tuple(hour_files),
                epoch_num=args.batch_epochs, batch_size=args.batch_size,
                max_nnz=FIELDS, learning_rate=args.lr, log_every=50,
                binary_cache=True,
                metrics_path=batch_jsonl, telemetry_run_id=run_id,
            ).validate()

        hour_files = []
        heldout = {}
        rows = []
        quiet = lambda *_: None
        for h in range(args.hours):
            labels, ids, vals = _gen_hour(h, args.hours, args.rows_per_hour, args.seed)
            # The online trainer's stream: APPEND hour h (timestamped
            # arrival), then follow up to the cumulative batch bound —
            # each hour after the first resumes MID-STREAM at the cursor.
            writer.append(labels, ids, vals.astype(np.float32))
            hf = os.path.join(tmp, f"hour_{h:02d}.libsvm")
            _write_libsvm(hf, labels, ids, vals)
            hour_files.append(hf)
            te_l, te_i, te_v = _gen_hour(
                h, args.hours, args.heldout_rows, args.seed + 1_000_003
            )
            te = os.path.join(tmp, f"heldout_{h:02d}.libsvm")
            _write_libsvm(te, te_l, te_i, te_v)
            heldout[h] = te

            cfg_on = online_cfg((h + 1) * batches_per_hour)
            train(cfg_on, resume=h > 0, log=quiet)
            cfg_ba = batch_cfg(hour_files)
            train(cfg_ba, log=quiet)

            if h + 1 >= args.hours:
                break
            # Prequential: both models score the NEXT hour before its
            # data arrives — the freshest question a CTR model answers.
            nh_l, nh_i, nh_v = _gen_hour(
                h + 1, args.hours, args.heldout_rows, args.seed + 1_000_003
            )
            nxt = os.path.join(tmp, f"heldout_{h + 1:02d}.libsvm")
            _write_libsvm(nxt, nh_l, nh_i, nh_v)
            heldout[h + 1] = nxt
            auc_on = float(_auc_on(cfg_on, nxt, FIELDS))
            auc_ba = float(_auc_on(cfg_ba, nxt, FIELDS))
            rows.append(
                {
                    "hour": h + 1,
                    "auc_online": round(auc_on, 5),
                    "auc_batch": round(auc_ba, 5),
                    "auc_gap": round(auc_ba - auc_on, 5),
                }
            )
            print(
                f"hour {h + 1:02d}: online {auc_on:.4f}  batch {auc_ba:.4f}  "
                f"gap {auc_ba - auc_on:+.4f}",
                flush=True,
            )
        writer.close()

        # kind=quality records ride the ONLINE run's telemetry stream —
        # report.py renders the table and --compare --strict gates it.
        mon = RunMonitor(online_jsonl, run_id=run_id, source="train")
        for r in rows:
            mon.emit("quality", step=r["hour"], **r)
        mon.close()

        # The report gate, run exactly as an operator would: online run
        # vs the batch reference's stream, strict.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "report_tool",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "report.py"),
        )
        report = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(report)
        s_on = report.summarize(report.load_run(online_jsonl))
        s_ba = report.summarize(report.load_run(batch_jsonl))
        cmp_text, regressions = report.compare(
            s_on, s_ba, threshold=args.threshold, strict=True
        )
        print(cmp_text)

        worst = max((r["auc_gap"] for r in rows), default=0.0)
        gate_ok = worst <= args.threshold and not any(
            "backtest" in r or "online" in r for r in regressions
        )
        from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact

        result = {
            **artifact_stamp(run_id),
            "tool": "backtest",
            "hours": args.hours,
            "rows_per_hour": args.rows_per_hour,
            "heldout_rows": args.heldout_rows,
            "batch_size": args.batch_size,
            "vocab": VOCAB,
            "fields": FIELDS,
            "factor_num": K,
            "lr": args.lr,
            "adagrad_decay": args.decay,
            "batch_epochs": args.batch_epochs,
            "drift": "60-degree planted-FM rotation over the day",
            "auc_by_hour": rows,
            "auc_online_mean": round(
                sum(r["auc_online"] for r in rows) / max(1, len(rows)), 5
            ),
            "auc_batch_mean": round(
                sum(r["auc_batch"] for r in rows) / max(1, len(rows)), 5
            ),
            "worst_hour_gap": round(worst, 5),
            "threshold": args.threshold,
            "gate": "OK" if gate_ok else "REGRESSED",
            "report_regressions": regressions,
        }
        write_json_artifact(args.out, result, sort_keys=False)
        print(f"wrote {args.out} (gate: {result['gate']})")
        return 0 if gate_ok else 1
    finally:
        _HANG_TIMER.cancel()
        if tmp_ctx is not None:
            tmp_ctx.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
