"""recompile-hazard: patterns that make steady state XLA-compile.

The zero-steady-recompile invariant is pinned at runtime by the compile
sentinel (telemetry.CompileSentinel), but only on paths a test drives.
This checker catches the constructions statically:

  * **jit-in-loop** — ``jax.jit(...)`` called lexically inside a
    for/while body (or in a def nested inside one): a fresh callable per
    iteration means a fresh trace+compile per iteration.
  * **uncached jit** — the PR-7 ``make_replicator`` class: a jit result
    built inside a function and neither returned, nor stored on
    self/module/class state, nor immediately stored into a cache
    container.  Each call of the enclosing function compiles again.
    (``jax.jit(f)(x)`` — construct-and-invoke — is the degenerate case.)
  * **traced Python scalar** — a known-jitted callable invoked with a
    raw loop variable argument: every distinct Python value retraces
    unless the arg is marked static or wrapped in an array.
  * **out-of-ledger lowering** — ``.lower(args)`` (with arguments — the
    zero-arg form is str.lower) or ``.cost_analysis()`` outside
    profiling.py: re-lowering is how the CostLedger measures cost
    WITHOUT a second backend compile, and it owns the one sanctioned
    call site; anywhere else risks paying compile twice.
  * **pallas-in-loop** — ``pl.pallas_call(...)`` lexically inside a
    for/while body: the same fresh-jit bug class as jit-in-loop (every
    iteration builds a fresh kernel callable → a fresh Mosaic compile).
    Only the loop-lexical form is flagged: construct-and-invoke inside a
    jitted function body (ops/pallas_*.py) traces once per program and
    caches with it — that is the normal Pallas idiom, not a hazard.
  * **interpret literal** — ``interpret=True`` written in a non-test
    module under ``fast_tffm_tpu/`` outside the shared helper
    (ops/pallas_common.py): a compiled path silently running kernels in
    the Pallas interpreter is an orders-of-magnitude throughput bug that
    no correctness test catches.  Production call sites pass
    ``interpret=None`` and let the helper resolve the backend.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    enclosing_function,
    jax_aliases,
    function_defs,
    parent_map,
    resolves_to,
)

RULE = "recompile-hazard"

# Files allowed to call .lower()/.cost_analysis(): the cost ledger owns
# re-lowering (one per program, off the hot path, documented in DESIGN
# §"Profiling & data statistics").
LOWER_ALLOWED = {"fast_tffm_tpu/profiling.py"}

# The one production module allowed to spell ``interpret=True``: the
# shared helper whose whole job is resolving the flag off the backend.
INTERPRET_ALLOWED = {"fast_tffm_tpu/ops/pallas_common.py"}


def _is_jit(call: ast.Call, aliases) -> bool:
    name = call_name(call)
    return name is not None and (
        resolves_to(name, "jax.jit", aliases)
        or resolves_to(name, "jax.pjit", aliases)
    )


def _is_pallas_call(call: ast.Call, aliases) -> bool:
    name = call_name(call)
    return name is not None and resolves_to(
        name, "jax.experimental.pallas.pallas_call", aliases
    )


def _jit_factories(tree: ast.AST, aliases) -> set[str]:
    """Local def qualnames that RETURN a jitted callable (directly, or a
    local bound to one) — the PR-14 interprocedural upgrade: ``step =
    make_step(...)`` makes ``step`` a known-jitted callable at its call
    sites, so the traced-scalar check sees through the helper."""
    out: set[str] = set()
    for qual, fn in function_defs(tree).items():
        jit_locals: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _is_jit(node.value, aliases):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jit_locals.add(tgt.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if isinstance(v, ast.Call) and _is_jit(v, aliases):
                    out.add(qual)
                elif isinstance(v, ast.Name) and v.id in jit_locals:
                    out.add(qual)
    return out


def _jit_callables(tree: ast.AST, aliases) -> set[str]:
    """Names (as written at call sites) bound to jitted callables in this
    module — the traced-scalar check's target set.  Includes names bound
    from a local jit FACTORY's return value (one call hop)."""
    out: set[str] = set()
    factories = _jit_factories(tree, aliases)
    factory_tails = {q.split(".")[-1] for q in factories}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit(node.value, aliases):
                for tgt in node.targets:
                    name = attr_chain(tgt)
                    if name:
                        out.add(name)
            else:
                cname = call_name(node.value)
                if cname is not None and (
                    cname in factories
                    or cname.split(".")[-1] in factory_tails
                ):
                    for tgt in node.targets:
                        name = attr_chain(tgt)
                        if name:
                            out.add(name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit(dec, aliases):
                    out.add(node.name)
                elif not isinstance(dec, ast.Call):
                    dname = attr_chain(dec)
                    if dname and resolves_to(dname, "jax.jit", aliases):
                        out.add(node.name)
    return out


def _loop_ancestors(node, parents):
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


class RecompileChecker:
    name = "recompile"
    rules = (RULE,)
    description = "constructions that compile in steady state"

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            aliases = jax_aliases(tree)
            parents = parent_map(tree)
            jitted = _jit_callables(tree, aliases)
            loop_vars = self._loop_vars(tree)
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and _is_jit(node, aliases):
                    findings.extend(
                        self._check_jit_site(sf, node, parents)
                    )
                if isinstance(node, ast.Call) and _is_pallas_call(node, aliases):
                    findings.extend(
                        self._check_pallas_site(sf, node, parents)
                    )
                if isinstance(node, ast.Call):
                    findings.extend(
                        self._check_traced_scalar(
                            sf, node, parents, jitted, loop_vars
                        )
                    )
                    findings.extend(self._check_lower(sf, node, parents))
                    findings.extend(
                        self._check_interpret_literal(sf, node, parents)
                    )
        return findings

    # -- jit construction sites ----------------------------------------

    def _check_jit_site(self, sf, call: ast.Call, parents):
        func_anchor = enclosing_function(call, parents)
        # (a) lexically inside a loop (crossing no function boundary —
        # a def inside the loop resets the judgment to the def's own
        # sinks, but the def CALL per iteration is the factory pattern
        # and factories are fine)
        for anc in _loop_ancestors(call, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                return [
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            "jax.jit called inside a loop — a fresh callable "
                            "(and a fresh trace+compile) per iteration"
                        ),
                        context=f"{func_anchor}:jit-in-loop",
                        fix_hint=(
                            "hoist the jit out of the loop, or cache the "
                            "callable keyed by what actually varies "
                            "(treedef/shape), as dist_train's replicator does"
                        ),
                    )
                ]
        # (b) uncached per-call construction
        sink = self._jit_sink(call, parents)
        if sink == "uncached":
            return [
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        "jitted callable constructed per call and never "
                        "cached — each invocation of "
                        f"{func_anchor.split('.')[-1]}() traces and "
                        "compiles again (the PR-7 fresh-jit-per-save class)"
                    ),
                    context=f"{func_anchor}:uncached-jit",
                    severity="warning",
                    fix_hint=(
                        "store the jitted fn on self/module at init, return "
                        "it from a factory, or memoize it in a dict keyed "
                        "by the varying part"
                    ),
                )
            ]
        return []

    def _jit_sink(self, call: ast.Call, parents) -> str:
        """'ok' when the jit result is cached/returned; 'uncached' when it
        is provably call-local (assigned to a local never returned, or
        invoked and discarded) inside a function."""
        parent = parents.get(call)
        fn = None
        for anc in _loop_ancestors(call, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = anc
                break
        if fn is None:
            return "ok"  # module/class level: compiled once per import
        # construct-and-invoke: jax.jit(f)(x)
        if isinstance(parent, ast.Call) and parent.func is call:
            return "uncached"
        if isinstance(parent, ast.Return):
            return "ok"  # factory
        if isinstance(parent, ast.Assign):
            local_names = []
            for tgt in parent.targets:
                name = attr_chain(tgt)
                if name is None:
                    return "ok"  # starred/subscript target: assume cached
                if "." in name or isinstance(tgt, ast.Subscript):
                    return "ok"  # self._f = jit(...) / cache[k] = jit(...)
                local_names.append(name)
            # a local: cached only if it escapes — returned, yielded,
            # stored onto an attribute/subscript, or closed over by a
            # returned def
            for name in local_names:
                if self._escapes(fn, name):
                    return "ok"
            return "uncached"
        # any other context (argument to a call, tuple element, with
        # item...): assume it escapes
        return "ok"

    @staticmethod
    def _value_reads(expr: ast.AST, name: str) -> bool:
        """Does ``name`` appear in ``expr`` as a VALUE (escaping), not
        merely as the func of a call?  ``return f`` escapes; ``return
        f(x)`` just uses the throwaway callable one time."""
        skip_funcs = {
            id(node.func)
            for node in ast.walk(expr)
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        }
        return any(
            isinstance(sub, ast.Name)
            and sub.id == name
            and id(sub) not in skip_funcs
            for sub in ast.walk(expr)
        )

    @classmethod
    def _escapes(cls, fn: ast.AST, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if cls._value_reads(node.value, name):
                    return True
            # stored beyond the frame: self.x = f / cache[k] = f
            if isinstance(node, ast.Assign):
                if cls._value_reads(node.value, name):
                    for tgt in node.targets:
                        if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                            return True
            # nested defs (closures) reading the name count as escapes —
            # the closure may be returned or stored
            if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                body = node.body if isinstance(node.body, list) else [node.body]
                for stmt in body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            return True
        return False

    # -- pallas_call construction sites --------------------------------

    def _check_pallas_site(self, sf, call: ast.Call, parents):
        """Loop-lexical check ONLY: ``pl.pallas_call(kernel, ...)(x)``
        construct-and-invoke inside a (jitted) function is the normal
        Pallas idiom — the trace caches with the enclosing program — so
        the uncached-sink analysis that applies to jax.jit would be all
        false positives here.  A pallas_call lexically inside a loop,
        though, is a fresh kernel (and a fresh Mosaic compile) per
        iteration: the same bug class as jit-in-loop."""
        func_anchor = enclosing_function(call, parents)
        for anc in _loop_ancestors(call, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                return [
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            "pl.pallas_call constructed inside a loop — a "
                            "fresh kernel callable (and a fresh Mosaic "
                            "compile) per iteration, the jit-in-loop bug "
                            "class"
                        ),
                        context=f"{func_anchor}:pallas-in-loop",
                        fix_hint=(
                            "hoist the pallas_call construction out of the "
                            "loop (grid/BlockSpec carry the per-iteration "
                            "variation), or wrap it in a cached factory"
                        ),
                    )
                ]
        return []

    # -- interpret=True literals ---------------------------------------

    def _check_interpret_literal(self, sf, call: ast.Call, parents):
        """``interpret=True`` in a production module silently swaps a
        compiled kernel for the Pallas interpreter — an orders-of-
        magnitude throughput bug no correctness test catches.  Only the
        shared helper (ops/pallas_common.py) may branch on the backend;
        production call sites pass ``interpret=None``."""
        if sf.rel in INTERPRET_ALLOWED or not sf.rel.startswith("fast_tffm_tpu/"):
            return []
        for kw in call.keywords:
            if (
                kw.arg == "interpret"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                return [
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            "interpret=True in a production module — this "
                            "path runs the kernel in the Pallas interpreter "
                            "even on TPU (a silent orders-of-magnitude "
                            "throughput bug)"
                        ),
                        context=(
                            f"{enclosing_function(call, parents)}:"
                            "interpret-literal"
                        ),
                        fix_hint=(
                            "pass interpret=None and let "
                            "ops.pallas_common.resolve_interpret pick the "
                            "backend; only tests spell interpret=True"
                        ),
                    )
                ]
        return []

    # -- traced Python scalars -----------------------------------------

    @staticmethod
    def _loop_vars(tree: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        return out

    def _check_traced_scalar(self, sf, call: ast.Call, parents, jitted, loop_vars):
        name = call_name(call)
        if name is None or name not in jitted:
            return []
        # only flag when the call site is itself inside a loop — a
        # loop var used once after the loop is a fixed value
        in_loop = any(
            isinstance(a, (ast.For, ast.While, ast.AsyncFor))
            for a in _loop_ancestors(call, parents)
        )
        if not in_loop:
            return []
        out = []
        for arg in call.args:
            if isinstance(arg, ast.Name) and arg.id in loop_vars:
                out.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            f"loop variable {arg.id!r} passed raw into jitted "
                            f"{name!r} — each distinct Python value retraces "
                            "and recompiles"
                        ),
                        context=(
                            f"{enclosing_function(call, parents)}:"
                            f"scalar:{arg.id}"
                        ),
                        severity="warning",
                        fix_hint=(
                            "wrap it (jnp.asarray / device_put) so the shape"
                            "/dtype is what's traced, or mark it static if "
                            "it really selects a program"
                        ),
                    )
                )
        return out

    # -- out-of-ledger lowering ----------------------------------------

    def _check_lower(self, sf, call: ast.Call, parents):
        if sf.rel in LOWER_ALLOWED or not sf.rel.startswith("fast_tffm_tpu/"):
            return []
        if not isinstance(call.func, ast.Attribute):
            return []
        attr = call.func.attr
        if attr == "cost_analysis" or (attr == "lower" and call.args):
            return [
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f".{attr}() outside the cost ledger — re-lowering "
                        "belongs to profiling.py (one per program, no second "
                        "backend compile); anywhere else risks compiling twice"
                    ),
                    context=f"{enclosing_function(call, parents)}:{attr}",
                    severity="warning",
                    fix_hint="route through profiling.CostLedger's delegated .lower",
                )
            ]
        return []
