"""Shared infrastructure for the invariant checker suite.

Everything a checker needs that is not rule logic lives here: the
``Finding`` model (rule, severity, stable baseline key, fix hint), the
parsed-source cache (``RepoContext`` parses each file once; all five
checkers share the ASTs), per-line suppression comments, the committed
baseline (pre-existing findings are pinned with a written justification;
any NEW finding fails ``--strict``), and the human/JSON renderers.

Stdlib-only on purpose: the suite must run on a machine that cannot
import jax (CI collectors, a laptop triaging a diff).

Suppression syntax, on the flagged line or the line directly above::

    # analysis: ok <rule> <reason>

The reason is REQUIRED — a bare suppression is itself an error finding
(rule ``suppression``), so silencing a rule always leaves a written
trace next to the code it excuses.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys

SEVERITIES = ("error", "warning")

# One place for every rule name so run.py, report.py and the tests agree.
RULES = (
    "donation-after-use",
    "recompile-hazard",
    "lock-discipline",
    "lock-order",
    "config-key",
    "telemetry",
    "format-drift",
    "atomic-publish",
    "exception-hygiene",
    "blocking-under-lock",
    "collective-divergence",
    "resource-lifecycle",
    "suppression",
    "parse",
)


@dataclasses.dataclass
class Finding:
    """One violation.  ``context`` is the stable anchor the baseline keys
    on (function/attr/key names — survives line-number drift, unlike
    ``line``, which is for humans and clickable editors).  ``ordinal``
    disambiguates same-context repeats (a SECOND uncached jit in the
    same function must read as NEW, not ride the first one's pin) —
    assigned by :func:`disambiguate` after a run."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = "error"
    context: str = ""
    fix_hint: str = ""
    ordinal: int = 1

    @property
    def key(self) -> str:
        base = f"{self.rule}::{self.path}::{self.context or self.message}"
        return base if self.ordinal <= 1 else f"{base}#{self.ordinal}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d

    def render(self) -> str:
        sev = "" if self.severity == "error" else f" [{self.severity}]"
        hint = f"\n      fix: {self.fix_hint}" if self.fix_hint else ""
        return f"{self.path}:{self.line}:{sev} {self.message}{hint}"


_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ok\s+([a-z][a-z0-9-]*)\b[ \t]*(.*)$"
)


# Process-wide parse cache keyed by (abspath, mtime_ns, size): the
# 11-rule suite builds several RepoContexts per process (the full run,
# a --changed-only pass, every test helper), and parsing + tokenizing
# ~100 files dominates its runtime.  Trees are read-only to every
# checker, so sharing them across contexts is safe; a touched file gets
# a new (mtime, size) key and re-parses.
_PARSE_CACHE: dict[tuple[str, int, int], dict] = {}


def _cache_key(abspath: str) -> tuple[str, int, int] | None:
    try:
        st = os.stat(abspath)
    except OSError:
        return None
    return (abspath, st.st_mtime_ns, st.st_size)


class SourceFile:
    """One parsed file: text, lines, AST (lazy), suppression map."""

    def __init__(self, abspath: str, rel: str):
        self.abspath = abspath
        self.rel = rel
        key = _cache_key(abspath)
        cached = _PARSE_CACHE.get(key) if key is not None else None
        if cached is not None:
            self.text = cached["text"]
            self.lines = cached["lines"]
            self._tree = cached["tree"]
            self._parse_error = cached["error"]
            self._parsed = cached["parsed"]
            self.suppressions = cached["suppressions"]
            return
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: ast.AST | None = None
        self._parse_error: SyntaxError | None = None
        self._parsed = False
        # line -> list[(rule, reason)]; reason may be "" (an error).
        # Tokenized, not line-regexed: the marker inside a STRING literal
        # ("# analysis: ok recompile-hazard ...") must not mute anything.
        self.suppressions: dict[int, list[tuple[str, str]]] = {}
        import io
        import tokenize

        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.text).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            tokens = []  # unparseable file: rule=parse reports it anyway
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                self.suppressions.setdefault(tok.start[0], []).append(
                    (m.group(1), m.group(2).strip())
                )
        if key is not None:
            self._cache_entry = key  # filled into _PARSE_CACHE post-parse

    @property
    def tree(self) -> ast.AST | None:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:
                self._parse_error = e
            key = getattr(self, "_cache_entry", None)
            if key is not None:
                _PARSE_CACHE[key] = {
                    "text": self.text,
                    "lines": self.lines,
                    "tree": self._tree,
                    "error": self._parse_error,
                    "parsed": True,
                    "suppressions": self.suppressions,
                }
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        self.tree  # trigger the lazy parse
        return self._parse_error

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding at ``line`` is suppressed by a REASONED ok-comment
        for its rule on the same line or the line directly above."""
        for ln in (line, line - 1):
            for r, reason in self.suppressions.get(ln, ()):
                if r == rule and reason:
                    return True
        return False


class RepoContext:
    """The shared input every checker runs against: the repo root and the
    parsed files.  Construction never raises on bad source — syntax
    errors surface as rule=``parse`` findings so one broken file cannot
    hide the rest of the report."""

    def __init__(self, root: str, rels: list[str]):
        self.root = os.path.abspath(root)
        self.files: list[SourceFile] = []
        self.parse_findings: list[Finding] = []
        for rel in sorted(rels):
            sf = SourceFile(os.path.join(self.root, rel), rel.replace(os.sep, "/"))
            self.files.append(sf)
            if sf.parse_error is not None:
                e = sf.parse_error
                self.parse_findings.append(
                    Finding(
                        rule="parse",
                        path=sf.rel,
                        line=e.lineno or 0,
                        message=f"syntax error: {e.msg}",
                        context=f"syntax:{e.lineno}",
                    )
                )

    def file(self, rel: str) -> SourceFile | None:
        rel = rel.replace(os.sep, "/")
        for sf in self.files:
            if sf.rel == rel:
                return sf
        return None

    def package_files(self, prefix: str = "fast_tffm_tpu/") -> list[SourceFile]:
        return [f for f in self.files if f.rel.startswith(prefix)]


DEFAULT_EXCLUDE_DIRS = {
    "__pycache__", ".git", "csrc", "docs", "configs", "tests"
}


def discover(root: str) -> list[str]:
    """Default target set: the package, tools (including this suite),
    and the top-level drivers.  tests/ is excluded on purpose — its
    fixtures (including test_analysis's own) violate rules by design.
    (PR 14 note: ``data`` used to be excluded here for the root-level
    dataset directory — but the walk never visits the root, and the
    entry silently pruned the ``fast_tffm_tpu/data`` PACKAGE out of the
    whole suite: the wire/binary/stream format modules were unanalyzed
    for a full PR cycle.  The format registries live exactly there, so
    the blind spot is gone.)"""
    rels: list[str] = []
    for base in ("fast_tffm_tpu", "tools"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, base)):
            dirnames[:] = [d for d in dirnames if d not in DEFAULT_EXCLUDE_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    rels.append(
                        os.path.relpath(os.path.join(dirpath, fn), root)
                    )
    for fn in ("bench.py", "bench_all.py", "fast_tffm.py"):
        if os.path.isfile(os.path.join(root, fn)):
            rels.append(fn)
    return rels


def disambiguate(findings: list[Finding]) -> list[Finding]:
    """Assign ordinals so same-base-key findings get distinct keys in
    source order ('...#2', '...#3').  Removing an occurrence shifts the
    survivors DOWN (never up), so a stale pin goes stale — it can never
    absorb a genuinely new occurrence."""
    counts: dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        f.ordinal = 1  # key reads the base form during the count
        n = counts.get(f.key, 0) + 1
        counts[f.key] = n
        f.ordinal = n
    return findings


# -- suppression application ----------------------------------------------


def apply_suppressions(
    findings: list[Finding], ctx: RepoContext
) -> list[Finding]:
    """Drop findings covered by a reasoned ok-comment; add one
    rule=``suppression`` error per REASON-LESS ok-comment anywhere in the
    tree (a silent mute is worse than the finding it hides)."""
    out = []
    for f in findings:
        sf = ctx.file(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            continue
        out.append(f)
    for sf in ctx.files:
        for ln, entries in sorted(sf.suppressions.items()):
            for rule, reason in entries:
                if not reason:
                    out.append(
                        Finding(
                            rule="suppression",
                            path=sf.rel,
                            line=ln,
                            message=(
                                f"suppression for {rule!r} has no reason — "
                                "'# analysis: ok <rule> <reason>' requires one"
                            ),
                            context=f"bare:{rule}:{ln}",
                            fix_hint="append the reason the rule is okay to break here",
                        )
                    )
                elif rule not in RULES:
                    out.append(
                        Finding(
                            rule="suppression",
                            path=sf.rel,
                            line=ln,
                            message=f"suppression names unknown rule {rule!r}",
                            context=f"unknown:{rule}:{ln}",
                            fix_hint="rules: " + ", ".join(r for r in RULES),
                        )
                    )
    return out


# -- baseline --------------------------------------------------------------


def load_baseline(path: str) -> dict:
    """{"version": 1, "pinned": [{key, justification, ...}]} → key map."""
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "pinned" not in data:
        raise ValueError(f"{path}: not a baseline file (no 'pinned' list)")
    out = {}
    for entry in data["pinned"]:
        out[entry["key"]] = entry
    return out


def write_baseline(
    path: str, findings: list[Finding], justifications=None, keep_entries=()
) -> None:
    """Pin the given findings.  ``justifications`` maps key (or rule, as
    a fallback) → text; unpinned-without-text entries get an empty
    justification, which --strict then refuses — writing a baseline is
    not the same as justifying it.  ``keep_entries`` carries existing
    pins to preserve verbatim (a partial --rules regeneration must not
    erase other checkers' debt)."""
    justifications = justifications or {}
    seen = set()
    pinned = []
    for entry in keep_entries:
        if entry["key"] not in seen:
            seen.add(entry["key"])
            pinned.append(entry)
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        if f.key in seen:
            continue
        seen.add(f.key)
        pinned.append(
            {
                "key": f.key,
                "rule": f.rule,
                "path": f.path,
                "severity": f.severity,
                "message": f.message,
                "justification": justifications.get(
                    f.key, justifications.get(f.rule, "")
                ),
            }
        )
    pinned.sort(key=lambda e: e["key"])
    with open(path, "w") as f:
        json.dump({"version": 1, "pinned": pinned}, f, indent=1, sort_keys=True)
        f.write("\n")


def partition(findings: list[Finding], baseline: dict):
    """(new, pinned, stale_keys): findings not in the baseline, findings
    the baseline covers, and baseline keys with no live finding (paid-off
    debt — prune them)."""
    new, pinned = [], []
    live_keys = set()
    for f in findings:
        live_keys.add(f.key)
        (pinned if f.key in baseline else new).append(f)
    stale = sorted(set(baseline) - live_keys)
    return new, pinned, stale


def unjustified(baseline: dict) -> list[str]:
    return sorted(
        k for k, e in baseline.items() if not (e.get("justification") or "").strip()
    )


# -- AST helpers shared by the checkers ------------------------------------


def attr_chain(node: ast.AST) -> str | None:
    """'self._mark', 'jax.jit', 'slot.lock' — or None when the expression
    is not a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return attr_chain(call.func)


def jax_aliases(tree: ast.AST) -> dict[str, str]:
    """Import-aware names: {'jit': 'jax.jit', 'partial':
    'functools.partial', ...} for this module."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolves_to(name: str, target: str, aliases: dict[str, str]) -> bool:
    """Does dotted ``name`` (as written) denote ``target`` (canonical,
    e.g. 'jax.jit') under this module's imports?"""
    if name == target:
        return True
    head, _, rest = name.partition(".")
    full = aliases.get(head)
    if full is None:
        return False
    return (full + ("." + rest if rest else "")) == target


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def enclosing_function(node: ast.AST, parents: dict) -> str:
    """Dotted qualname-ish anchor: 'Router._on_down' / '<module>'."""
    names = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>"


# -- interprocedural call graph (PR 14) -------------------------------------
#
# One module, one graph: every def (module-level 'helper', methods as
# 'Class.method') plus the calls each makes, with call-site spellings
# resolved back to local defs where possible ('helper' → helper;
# 'self.m' → '<enclosing class>.m').  Deliberately ONE module deep and
# ONE hop at a time: the checkers that ride it (donation wrappers,
# factory-returned jit callables) follow a single call boundary, which
# is where the historical bugs lived — a whole-repo fixpoint would buy
# noise, not signal.


def function_defs(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    """Qualname → def node: module-level defs under their bare name,
    methods as 'Class.method'.  Nested (closure) defs are skipped — they
    are not callable from outside their scope."""
    out: dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


class CallGraph:
    """``defs``: qualname → def node.  ``calls``: caller qualname →
    [(callee spelling as written, Call node)].  ``resolve`` maps a
    spelling at a call site inside ``caller`` to a local def qualname
    (or None for externals)."""

    def __init__(self, defs, calls):
        self.defs = defs
        self.calls = calls

    def resolve(self, caller: str, spelling: str) -> str | None:
        if spelling in self.defs:
            return spelling
        head, _, rest = spelling.partition(".")
        if head == "self" and rest and "." in caller:
            qual = f"{caller.split('.')[0]}.{rest.split('.')[0]}"
            if qual in self.defs:
                return qual
        return None

    def callees(self, caller: str):
        """Resolved (qualname, Call) pairs for one caller."""
        for spelling, call in self.calls.get(caller, ()):
            qual = self.resolve(caller, spelling)
            if qual is not None:
                yield qual, call


def _walk_own_scope(fn: ast.AST):
    """Nodes of ``fn``'s body excluding nested def/class bodies (those
    are their own scopes; a closure's calls are not the enclosing def's)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def module_call_graph(tree: ast.AST) -> CallGraph:
    defs = function_defs(tree)
    calls: dict[str, list] = {q: [] for q in defs}
    for qual, fn in defs.items():
        for node in _walk_own_scope(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name is not None:
                    calls[qual].append((name, node))
    return CallGraph(defs, calls)


# -- intraprocedural CFG + forward dataflow (PR 15) --------------------------
#
# The flow-sensitive core the concurrency checkers ride: basic blocks
# over if/for/while/try/with, one node per statement occurrence, plus a
# generic forward "facts held here" fixpoint.  Deliberately small:
# no expression-level flow, no interprocedural edges (module_call_graph
# above supplies the one-hop composition), exception edges approximated
# as "any statement inside a try can jump to its handlers".  That is
# exactly enough to answer the questions the checkers ask — which locks
# are held AT this statement, can this function leave without reaching
# a cleanup — without modelling Python it doesn't need.


class CFGNode:
    """One statement occurrence.  ``with_items`` is the lexical stack of
    ``with`` context expressions entered around this statement (innermost
    last) — with-scoped facts (lock held) are precise lexically, so they
    ride the node instead of the dataflow.  ``kind`` ∈ stmt | entry |
    exit."""

    __slots__ = ("stmt", "kind", "succ", "pred", "with_items", "index")

    def __init__(self, stmt=None, kind="stmt", with_items=()):
        self.stmt = stmt
        self.kind = kind
        self.succ: list[CFGNode] = []
        self.pred: list[CFGNode] = []
        self.with_items = tuple(with_items)
        self.index = -1

    def link(self, other: "CFGNode") -> None:
        if other not in self.succ:
            self.succ.append(other)
            other.pred.append(self)

    def own_exprs(self) -> tuple:
        """The AST subtrees that execute AT this node.  A compound
        statement's node is its HEADER (test/iter/subject/context
        expressions) — the body statements have their own nodes, so
        transfer functions and call scans must not walk the subtree
        twice."""
        s = self.stmt
        if s is None:
            return ()
        if isinstance(s, (ast.If, ast.While)):
            return (s.test,)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return (s.iter,)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return tuple(item.context_expr for item in s.items)
        if isinstance(s, ast.Match):
            return (s.subject,)
        if isinstance(
            s,
            (ast.Try, ast.ExceptHandler, ast.FunctionDef,
             ast.AsyncFunctionDef, ast.ClassDef),
        ):
            return ()
        return (s,)

    def __repr__(self):  # pragma: no cover - debugging aid
        what = self.kind if self.kind != "stmt" else type(self.stmt).__name__
        return f"<CFGNode {self.index} {what}>"


class CFG:
    """entry → statement nodes → exit.  ``nodes`` excludes entry/exit;
    ``by_stmt`` maps a statement AST node to its CFGNode (headers of
    compound statements get the node; their bodies get their own)."""

    def __init__(self):
        self.entry = CFGNode(kind="entry")
        self.exit = CFGNode(kind="exit")
        self.nodes: list[CFGNode] = []
        self.by_stmt: dict[ast.AST, CFGNode] = {}

    def _new(self, stmt, with_items) -> CFGNode:
        node = CFGNode(stmt, with_items=with_items)
        node.index = len(self.nodes)
        self.nodes.append(node)
        self.by_stmt[stmt] = node
        return node


class _CFGBuilder:
    """Recursive-descent CFG construction.  The frontier is the set of
    nodes whose control continues at the NEXT statement; terminators
    (return/raise/break/continue) empty it."""

    def __init__(self):
        self.cfg = CFG()
        self._breaks: list[list[CFGNode]] = []
        self._loop_heads: list[CFGNode] = []
        self._handlers: list[list[CFGNode]] = []  # enclosing try handler heads
        self._with: list[ast.expr] = []
        # Returns (and unhandled raises) inside a try-with-finally run the
        # finalbody on the way out: they park here and become extra preds
        # of the finally instead of edges straight to exit.
        self._final_pending: list[list[CFGNode]] = []

    def build(self, fn) -> CFG:
        frontier = self._seq(fn.body, [self.cfg.entry])
        for node in frontier:
            node.link(self.cfg.exit)
        return self.cfg

    def _seq(self, body, preds) -> list[CFGNode]:
        # An empty frontier (code after a terminator, a finally whose try
        # always exits) still gets nodes — predecessor-less, so dataflow
        # treats them as unreached — because by_stmt must cover every
        # statement the lexical checks ask about.
        frontier = list(preds)
        for stmt in body:
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _node(self, stmt, preds) -> CFGNode:
        node = self.cfg._new(stmt, tuple(self._with))
        for p in preds:
            p.link(node)
        # Conservative exception edge: any statement inside a try may
        # transfer to its (innermost) handlers — or, in a finally-only
        # try, straight into the finalbody (the exception runs it on the
        # way out, so the finally must meet every body statement's OUT,
        # including pre-acquire ones).
        if self._handlers:
            for h in self._handlers[-1]:
                node.link(h)
        elif self._final_pending:
            self._final_pending[-1].append(node)
        return node

    def _stmt(self, stmt, preds) -> list[CFGNode]:
        if isinstance(stmt, ast.If):
            test = self._node(stmt, preds)
            then_f = self._seq(stmt.body, [test])
            else_f = self._seq(stmt.orelse, [test]) if stmt.orelse else [test]
            return then_f + else_f
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._node(stmt, preds)
            self._breaks.append([])
            self._loop_heads.append(head)
            body_f = self._seq(stmt.body, [head])
            for node in body_f:
                node.link(head)  # back edge
            self._loop_heads.pop()
            breaks = self._breaks.pop()
            else_f = self._seq(stmt.orelse, [head]) if stmt.orelse else [head]
            return else_f + breaks
        if isinstance(stmt, ast.Try):
            handler_heads = [
                self.cfg._new(h, tuple(self._with)) for h in stmt.handlers
            ]
            if stmt.finalbody:
                self._final_pending.append([])
            # Only a try WITH handlers claims the exception edges — an
            # empty list on the stack would swallow raises in a
            # finally-only try instead of routing them to the finalbody.
            if handler_heads:
                self._handlers.append(handler_heads)
            body_f = self._seq(stmt.body, preds)
            if handler_heads:
                self._handlers.pop()
            for p in preds:  # an exception can fire before any body stmt ran
                for h in handler_heads:
                    p.link(h)
            out = []
            for head, h in zip(handler_heads, stmt.handlers):
                out += self._seq(h.body, [head])
            out += self._seq(stmt.orelse, body_f) if stmt.orelse else body_f
            if stmt.finalbody:
                pending = self._final_pending.pop()
                # Return/raise paths meet the normal fall-through at the
                # finally's entry (conservative: after the finally they
                # continue with the frontier rather than forking back to
                # exit — extra predecessors only shrink must-facts).
                out = self._seq(stmt.finalbody, out + pending)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._node(stmt, preds)
            self._with.extend(item.context_expr for item in stmt.items)
            body_f = self._seq(stmt.body, [head])
            del self._with[len(self._with) - len(stmt.items):]
            return body_f
        if isinstance(stmt, ast.Match):
            subject = self._node(stmt, preds)
            out = [subject]  # no case may match
            for case in stmt.cases:
                out += self._seq(case.body, [subject])
            return out
        # simple statements (incl. nested def/class, one opaque node each)
        node = self._node(stmt, preds)
        if isinstance(stmt, ast.Return):
            if self._final_pending:
                self._final_pending[-1].append(node)
            else:
                node.link(self.cfg.exit)
            return []
        if isinstance(stmt, ast.Raise):
            if self._handlers:
                for h in self._handlers[-1]:
                    node.link(h)
            elif self._final_pending:
                self._final_pending[-1].append(node)
            else:
                node.link(self.cfg.exit)
            return []
        if isinstance(stmt, ast.Break):
            if self._breaks:
                self._breaks[-1].append(node)
            return []
        if isinstance(stmt, ast.Continue):
            if self._loop_heads:
                node.link(self._loop_heads[-1])
            return []
        return [node]


def build_cfg(fn) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef (nested defs are opaque
    single nodes — they execute later, on their own CFG)."""
    return _CFGBuilder().build(fn)


def forward_must(cfg: CFG, gen_kill) -> dict[CFGNode, frozenset]:
    """Forward MUST dataflow to fixpoint: fact sets meet by intersection
    at joins (a fact holds at a statement only if it holds on EVERY path
    reaching it — the right polarity for "lock held here", where a maybe
    is not an is).  ``gen_kill(node) -> (gen, kill)``.  Returns the IN
    fact per node (facts established BEFORE the statement runs); TOP
    (unvisited) is represented internally as None.  Convergence is
    guaranteed: facts only leave a set at a kill, and intersection is
    monotone on the finite fact universe."""
    IN: dict[CFGNode, frozenset | None] = {cfg.entry: frozenset()}
    OUT: dict[CFGNode, frozenset | None] = {cfg.entry: frozenset()}
    work = list(cfg.entry.succ)
    while work:
        node = work.pop()
        acc = None
        for p in node.pred:
            po = OUT.get(p)
            if po is None:
                continue  # TOP: identity for intersection
            acc = po if acc is None else (acc & po)
        if acc is None:
            continue  # no computed predecessor yet
        gen, kill = gen_kill(node)
        out = (acc - frozenset(kill)) | frozenset(gen)
        if IN.get(node) != acc or OUT.get(node) != out:
            IN[node] = acc
            OUT[node] = out
            work.extend(node.succ)
    return {n: (IN.get(n) if IN.get(n) is not None else frozenset())
            for n in cfg.nodes}


def reaches_without(cfg: CFG, start: CFGNode, stop_pred) -> bool:
    """May-escape query: is ``cfg.exit`` reachable from ``start`` without
    passing through a node satisfying ``stop_pred``?  The lifecycle
    checker's core question — can control leave the function while the
    resource acquired at ``start`` has seen no cleanup."""
    seen = set()
    work = list(start.succ)
    while work:
        node = work.pop()
        if node is cfg.exit:
            return True
        if id(node) in seen:
            continue
        seen.add(id(node))
        if node.kind == "stmt" and stop_pred(node):
            continue
        work.extend(node.succ)
    return False


# -- output ----------------------------------------------------------------


def render_text(
    findings: list[Finding], new: list[Finding], stale: list[str],
    baseline: dict, strict: bool,
) -> str:
    L = []
    by_rule: dict[str, list[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        fs = by_rule[rule]
        L.append(f"[{rule}] {len(fs)} finding(s):")
        for f in sorted(fs, key=lambda f: (f.path, f.line)):
            mark = "NEW " if f in new else ""
            L.append(f"  {mark}{f.render()}")
    errs = sum(1 for f in findings if f.severity == "error")
    L.append(
        f"analysis: {len(findings)} finding(s) ({errs} error(s)), "
        f"{len(new)} new vs baseline, {len(baseline)} pinned, {len(stale)} stale"
    )
    if stale:
        L.append(
            "stale baseline entries (debt paid off — prune them from the "
            "baseline file):"
        )
        L += [f"  {k}" for k in stale]
    bad = unjustified(baseline)
    if bad and strict:
        L.append("baseline entries missing a justification:")
        L += [f"  {k}" for k in bad]
    return "\n".join(L)


def to_json(findings, new, stale, baseline, root) -> dict:
    by_rule: dict[str, int] = {}
    by_sev: dict[str, int] = {}
    debt_by_rule: dict[str, int] = {}
    new_keys = {f.key for f in new}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
        if f.key not in new_keys:
            debt_by_rule[f.rule] = debt_by_rule.get(f.rule, 0) + 1
    return {
        "version": 1,
        "root": root,
        "counts": {"by_rule": by_rule, "by_severity": by_sev},
        "baseline": {
            "pinned": len(baseline),
            "stale": len(stale),
            "unjustified": len(unjustified(baseline)),
            "debt": len(findings) - len(new),
            "debt_by_rule": debt_by_rule,
        },
        # The lockfile gate's input: ANY live format-drift finding —
        # pinned or not — is persisted-format drift (pinning drift in the
        # baseline must not hide it from the report gate).
        "lock_drift": by_rule.get("format-drift", 0),
        "new": [f.to_dict() for f in new],
        "findings": [f.to_dict() for f in findings],
    }


def _tools_on_path() -> None:
    tools = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if tools not in sys.path:
        sys.path.insert(0, tools)


_tools_on_path()
