"""Shared infrastructure for the invariant checker suite.

Everything a checker needs that is not rule logic lives here: the
``Finding`` model (rule, severity, stable baseline key, fix hint), the
parsed-source cache (``RepoContext`` parses each file once; all five
checkers share the ASTs), per-line suppression comments, the committed
baseline (pre-existing findings are pinned with a written justification;
any NEW finding fails ``--strict``), and the human/JSON renderers.

Stdlib-only on purpose: the suite must run on a machine that cannot
import jax (CI collectors, a laptop triaging a diff).

Suppression syntax, on the flagged line or the line directly above::

    # analysis: ok <rule> <reason>

The reason is REQUIRED — a bare suppression is itself an error finding
(rule ``suppression``), so silencing a rule always leaves a written
trace next to the code it excuses.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys

SEVERITIES = ("error", "warning")

# One place for every rule name so run.py, report.py and the tests agree.
RULES = (
    "donation-after-use",
    "recompile-hazard",
    "lock-discipline",
    "lock-order",
    "config-key",
    "telemetry",
    "format-drift",
    "atomic-publish",
    "exception-hygiene",
    "suppression",
    "parse",
)


@dataclasses.dataclass
class Finding:
    """One violation.  ``context`` is the stable anchor the baseline keys
    on (function/attr/key names — survives line-number drift, unlike
    ``line``, which is for humans and clickable editors).  ``ordinal``
    disambiguates same-context repeats (a SECOND uncached jit in the
    same function must read as NEW, not ride the first one's pin) —
    assigned by :func:`disambiguate` after a run."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = "error"
    context: str = ""
    fix_hint: str = ""
    ordinal: int = 1

    @property
    def key(self) -> str:
        base = f"{self.rule}::{self.path}::{self.context or self.message}"
        return base if self.ordinal <= 1 else f"{base}#{self.ordinal}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d

    def render(self) -> str:
        sev = "" if self.severity == "error" else f" [{self.severity}]"
        hint = f"\n      fix: {self.fix_hint}" if self.fix_hint else ""
        return f"{self.path}:{self.line}:{sev} {self.message}{hint}"


_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ok\s+([a-z][a-z0-9-]*)\b[ \t]*(.*)$"
)


class SourceFile:
    """One parsed file: text, lines, AST (lazy), suppression map."""

    def __init__(self, abspath: str, rel: str):
        self.abspath = abspath
        self.rel = rel
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: ast.AST | None = None
        self._parse_error: SyntaxError | None = None
        # line -> list[(rule, reason)]; reason may be "" (an error).
        # Tokenized, not line-regexed: the marker inside a STRING literal
        # ("# analysis: ok recompile-hazard ...") must not mute anything.
        self.suppressions: dict[int, list[tuple[str, str]]] = {}
        import io
        import tokenize

        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.text).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            tokens = []  # unparseable file: rule=parse reports it anyway
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                self.suppressions.setdefault(tok.start[0], []).append(
                    (m.group(1), m.group(2).strip())
                )

    @property
    def tree(self) -> ast.AST | None:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        self.tree  # trigger the lazy parse
        return self._parse_error

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding at ``line`` is suppressed by a REASONED ok-comment
        for its rule on the same line or the line directly above."""
        for ln in (line, line - 1):
            for r, reason in self.suppressions.get(ln, ()):
                if r == rule and reason:
                    return True
        return False


class RepoContext:
    """The shared input every checker runs against: the repo root and the
    parsed files.  Construction never raises on bad source — syntax
    errors surface as rule=``parse`` findings so one broken file cannot
    hide the rest of the report."""

    def __init__(self, root: str, rels: list[str]):
        self.root = os.path.abspath(root)
        self.files: list[SourceFile] = []
        self.parse_findings: list[Finding] = []
        for rel in sorted(rels):
            sf = SourceFile(os.path.join(self.root, rel), rel.replace(os.sep, "/"))
            self.files.append(sf)
            if sf.parse_error is not None:
                e = sf.parse_error
                self.parse_findings.append(
                    Finding(
                        rule="parse",
                        path=sf.rel,
                        line=e.lineno or 0,
                        message=f"syntax error: {e.msg}",
                        context=f"syntax:{e.lineno}",
                    )
                )

    def file(self, rel: str) -> SourceFile | None:
        rel = rel.replace(os.sep, "/")
        for sf in self.files:
            if sf.rel == rel:
                return sf
        return None

    def package_files(self, prefix: str = "fast_tffm_tpu/") -> list[SourceFile]:
        return [f for f in self.files if f.rel.startswith(prefix)]


DEFAULT_EXCLUDE_DIRS = {
    "__pycache__", ".git", "csrc", "docs", "configs", "tests"
}


def discover(root: str) -> list[str]:
    """Default target set: the package, tools (including this suite),
    and the top-level drivers.  tests/ is excluded on purpose — its
    fixtures (including test_analysis's own) violate rules by design.
    (PR 14 note: ``data`` used to be excluded here for the root-level
    dataset directory — but the walk never visits the root, and the
    entry silently pruned the ``fast_tffm_tpu/data`` PACKAGE out of the
    whole suite: the wire/binary/stream format modules were unanalyzed
    for a full PR cycle.  The format registries live exactly there, so
    the blind spot is gone.)"""
    rels: list[str] = []
    for base in ("fast_tffm_tpu", "tools"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, base)):
            dirnames[:] = [d for d in dirnames if d not in DEFAULT_EXCLUDE_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    rels.append(
                        os.path.relpath(os.path.join(dirpath, fn), root)
                    )
    for fn in ("bench.py", "bench_all.py", "fast_tffm.py"):
        if os.path.isfile(os.path.join(root, fn)):
            rels.append(fn)
    return rels


def disambiguate(findings: list[Finding]) -> list[Finding]:
    """Assign ordinals so same-base-key findings get distinct keys in
    source order ('...#2', '...#3').  Removing an occurrence shifts the
    survivors DOWN (never up), so a stale pin goes stale — it can never
    absorb a genuinely new occurrence."""
    counts: dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        f.ordinal = 1  # key reads the base form during the count
        n = counts.get(f.key, 0) + 1
        counts[f.key] = n
        f.ordinal = n
    return findings


# -- suppression application ----------------------------------------------


def apply_suppressions(
    findings: list[Finding], ctx: RepoContext
) -> list[Finding]:
    """Drop findings covered by a reasoned ok-comment; add one
    rule=``suppression`` error per REASON-LESS ok-comment anywhere in the
    tree (a silent mute is worse than the finding it hides)."""
    out = []
    for f in findings:
        sf = ctx.file(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            continue
        out.append(f)
    for sf in ctx.files:
        for ln, entries in sorted(sf.suppressions.items()):
            for rule, reason in entries:
                if not reason:
                    out.append(
                        Finding(
                            rule="suppression",
                            path=sf.rel,
                            line=ln,
                            message=(
                                f"suppression for {rule!r} has no reason — "
                                "'# analysis: ok <rule> <reason>' requires one"
                            ),
                            context=f"bare:{rule}:{ln}",
                            fix_hint="append the reason the rule is okay to break here",
                        )
                    )
                elif rule not in RULES:
                    out.append(
                        Finding(
                            rule="suppression",
                            path=sf.rel,
                            line=ln,
                            message=f"suppression names unknown rule {rule!r}",
                            context=f"unknown:{rule}:{ln}",
                            fix_hint="rules: " + ", ".join(r for r in RULES),
                        )
                    )
    return out


# -- baseline --------------------------------------------------------------


def load_baseline(path: str) -> dict:
    """{"version": 1, "pinned": [{key, justification, ...}]} → key map."""
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "pinned" not in data:
        raise ValueError(f"{path}: not a baseline file (no 'pinned' list)")
    out = {}
    for entry in data["pinned"]:
        out[entry["key"]] = entry
    return out


def write_baseline(
    path: str, findings: list[Finding], justifications=None, keep_entries=()
) -> None:
    """Pin the given findings.  ``justifications`` maps key (or rule, as
    a fallback) → text; unpinned-without-text entries get an empty
    justification, which --strict then refuses — writing a baseline is
    not the same as justifying it.  ``keep_entries`` carries existing
    pins to preserve verbatim (a partial --rules regeneration must not
    erase other checkers' debt)."""
    justifications = justifications or {}
    seen = set()
    pinned = []
    for entry in keep_entries:
        if entry["key"] not in seen:
            seen.add(entry["key"])
            pinned.append(entry)
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        if f.key in seen:
            continue
        seen.add(f.key)
        pinned.append(
            {
                "key": f.key,
                "rule": f.rule,
                "path": f.path,
                "severity": f.severity,
                "message": f.message,
                "justification": justifications.get(
                    f.key, justifications.get(f.rule, "")
                ),
            }
        )
    pinned.sort(key=lambda e: e["key"])
    with open(path, "w") as f:
        json.dump({"version": 1, "pinned": pinned}, f, indent=1, sort_keys=True)
        f.write("\n")


def partition(findings: list[Finding], baseline: dict):
    """(new, pinned, stale_keys): findings not in the baseline, findings
    the baseline covers, and baseline keys with no live finding (paid-off
    debt — prune them)."""
    new, pinned = [], []
    live_keys = set()
    for f in findings:
        live_keys.add(f.key)
        (pinned if f.key in baseline else new).append(f)
    stale = sorted(set(baseline) - live_keys)
    return new, pinned, stale


def unjustified(baseline: dict) -> list[str]:
    return sorted(
        k for k, e in baseline.items() if not (e.get("justification") or "").strip()
    )


# -- AST helpers shared by the checkers ------------------------------------


def attr_chain(node: ast.AST) -> str | None:
    """'self._mark', 'jax.jit', 'slot.lock' — or None when the expression
    is not a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return attr_chain(call.func)


def jax_aliases(tree: ast.AST) -> dict[str, str]:
    """Import-aware names: {'jit': 'jax.jit', 'partial':
    'functools.partial', ...} for this module."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def resolves_to(name: str, target: str, aliases: dict[str, str]) -> bool:
    """Does dotted ``name`` (as written) denote ``target`` (canonical,
    e.g. 'jax.jit') under this module's imports?"""
    if name == target:
        return True
    head, _, rest = name.partition(".")
    full = aliases.get(head)
    if full is None:
        return False
    return (full + ("." + rest if rest else "")) == target


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def enclosing_function(node: ast.AST, parents: dict) -> str:
    """Dotted qualname-ish anchor: 'Router._on_down' / '<module>'."""
    names = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>"


# -- interprocedural call graph (PR 14) -------------------------------------
#
# One module, one graph: every def (module-level 'helper', methods as
# 'Class.method') plus the calls each makes, with call-site spellings
# resolved back to local defs where possible ('helper' → helper;
# 'self.m' → '<enclosing class>.m').  Deliberately ONE module deep and
# ONE hop at a time: the checkers that ride it (donation wrappers,
# factory-returned jit callables) follow a single call boundary, which
# is where the historical bugs lived — a whole-repo fixpoint would buy
# noise, not signal.


def function_defs(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    """Qualname → def node: module-level defs under their bare name,
    methods as 'Class.method'.  Nested (closure) defs are skipped — they
    are not callable from outside their scope."""
    out: dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


class CallGraph:
    """``defs``: qualname → def node.  ``calls``: caller qualname →
    [(callee spelling as written, Call node)].  ``resolve`` maps a
    spelling at a call site inside ``caller`` to a local def qualname
    (or None for externals)."""

    def __init__(self, defs, calls):
        self.defs = defs
        self.calls = calls

    def resolve(self, caller: str, spelling: str) -> str | None:
        if spelling in self.defs:
            return spelling
        head, _, rest = spelling.partition(".")
        if head == "self" and rest and "." in caller:
            qual = f"{caller.split('.')[0]}.{rest.split('.')[0]}"
            if qual in self.defs:
                return qual
        return None

    def callees(self, caller: str):
        """Resolved (qualname, Call) pairs for one caller."""
        for spelling, call in self.calls.get(caller, ()):
            qual = self.resolve(caller, spelling)
            if qual is not None:
                yield qual, call


def _walk_own_scope(fn: ast.AST):
    """Nodes of ``fn``'s body excluding nested def/class bodies (those
    are their own scopes; a closure's calls are not the enclosing def's)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def module_call_graph(tree: ast.AST) -> CallGraph:
    defs = function_defs(tree)
    calls: dict[str, list] = {q: [] for q in defs}
    for qual, fn in defs.items():
        for node in _walk_own_scope(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name is not None:
                    calls[qual].append((name, node))
    return CallGraph(defs, calls)


# -- output ----------------------------------------------------------------


def render_text(
    findings: list[Finding], new: list[Finding], stale: list[str],
    baseline: dict, strict: bool,
) -> str:
    L = []
    by_rule: dict[str, list[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        fs = by_rule[rule]
        L.append(f"[{rule}] {len(fs)} finding(s):")
        for f in sorted(fs, key=lambda f: (f.path, f.line)):
            mark = "NEW " if f in new else ""
            L.append(f"  {mark}{f.render()}")
    errs = sum(1 for f in findings if f.severity == "error")
    L.append(
        f"analysis: {len(findings)} finding(s) ({errs} error(s)), "
        f"{len(new)} new vs baseline, {len(baseline)} pinned, {len(stale)} stale"
    )
    if stale:
        L.append(
            "stale baseline entries (debt paid off — prune them from the "
            "baseline file):"
        )
        L += [f"  {k}" for k in stale]
    bad = unjustified(baseline)
    if bad and strict:
        L.append("baseline entries missing a justification:")
        L += [f"  {k}" for k in bad]
    return "\n".join(L)


def to_json(findings, new, stale, baseline, root) -> dict:
    by_rule: dict[str, int] = {}
    by_sev: dict[str, int] = {}
    debt_by_rule: dict[str, int] = {}
    new_keys = {f.key for f in new}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
        if f.key not in new_keys:
            debt_by_rule[f.rule] = debt_by_rule.get(f.rule, 0) + 1
    return {
        "version": 1,
        "root": root,
        "counts": {"by_rule": by_rule, "by_severity": by_sev},
        "baseline": {
            "pinned": len(baseline),
            "stale": len(stale),
            "unjustified": len(unjustified(baseline)),
            "debt": len(findings) - len(new),
            "debt_by_rule": debt_by_rule,
        },
        # The lockfile gate's input: ANY live format-drift finding —
        # pinned or not — is persisted-format drift (pinning drift in the
        # baseline must not hide it from the report gate).
        "lock_drift": by_rule.get("format-drift", 0),
        "new": [f.to_dict() for f in new],
        "findings": [f.to_dict() for f in findings],
    }


def _tools_on_path() -> None:
    tools = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if tools not in sys.path:
        sys.path.insert(0, tools)


_tools_on_path()
