"""blocking-under-lock: unbounded waits while holding a lock.

The flow bug class that actually cost this repo review cycles: PR 8's
router v1 conflated data and control connections so health pings queued
behind a blocked socket; PR 6's prefetch consumer wedged forever in
``q.get()``; the readiness waiter parked in ``readline`` on a silent
child.  Each is the same shape — a call that can block UNBOUNDEDLY
executed while a lock is held, turning one slow/dead peer into a
whole-object deadlock (every other thread then queues on the lock).

Flow-sensitive on the PR-15 CFG core: the set of locks held at each
statement is the lexical ``with``-stack of its CFG node plus a forward
MUST-dataflow over explicit ``.acquire()``/``.release()`` pairs (a lock
counts as held only when it is held on EVERY path reaching the
statement — branches that may or may not have acquired stay quiet).
Lock identities come from the PR-13 catalogs: ``self.X =
threading.Lock()`` class attributes, annotation-typed cross-object
locks (``slot: _Slot`` → ``slot.lock``), and module-level locks.

What counts as blocking (the timeout allowlist — a bounded wait is not
a finding):

  * ``queue.get()`` / ``.join()`` / ``.wait()`` / ``.result()`` with no
    timeout (argument or keyword) — Queue, Thread, Event, Condition,
    Popen, Future all spell their bounded forms the same way;
  * ``subprocess.run/check_call/check_output/communicate`` without
    ``timeout=``;
  * socket ops — ``recv``/``recv_into``/``accept``/``send``/``sendall``,
    and ``readline``/``read`` on a socket-backed file or subprocess
    pipe — unless the module establishes a deadline for that endpoint
    (``settimeout(...)`` with a non-None value, or
    ``create_connection(..., timeout=...)``); the evidence is tracked by
    endpoint name through makefile()/attribute hand-offs.

``os.replace`` and plain file I/O are deliberately NOT in the set (they
block on disk, not on a peer), and a with-lock body that only snapshots
counters — the sanctioned leaf-lock pattern — has nothing to flag.

One-hop interprocedural composition (PR-14 call graph): a call made
while a lock is held into a same-module function whose own body blocks
unboundedly is flagged at the call site — the PR-7-era "the lock is in
the caller, the wait is in the callee" split must not hide the pair.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    build_cfg,
    call_name,
    forward_must,
    function_defs,
    jax_aliases,
    module_call_graph,
    resolves_to,
)
from analysis.check_locks import _lock_attrs_of

RULE = "blocking-under-lock"

_SOCKET_ONLY_TAILS = {"recv", "recv_into", "accept", "sendall", "send"}
_STREAM_TAILS = {"readline", "readlines", "read"}
_PIPE_SEGMENTS = {"stdout", "stderr", "stdin", "rfile"}
_SUBPROCESS_FNS = (
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.call",
)


def _leaf(expr) -> str | None:
    chain = attr_chain(expr)
    if chain is None:
        return None
    return chain.split(".")[-1]


def _has_timeout_kw(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return True
    return False


class _SocketFacts:
    """Per-module endpoint tracking: which names denote socket/pipe-like
    endpoints, and which of those have deadline evidence.  Keyed by LEAF
    name (``slot.sock`` and the local ``sock`` meet at ``sock``) — the
    coarse join is deliberate: one settimeout on an endpoint name is
    read as that endpoint's policy module-wide."""

    def __init__(self, tree: ast.AST, aliases):
        self.socketish: set[str] = set()
        self.bounded: set[str] = set()
        makefile_edges: list[tuple[str, str]] = []
        alias_edges: list[tuple[str, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and name.split(".")[-1] == "settimeout":
                    args = node.args
                    if args and not (
                        isinstance(args[0], ast.Constant) and args[0].value is None
                    ):
                        base = _leaf(node.func.value) if isinstance(
                            node.func, ast.Attribute
                        ) else None
                        if base:
                            self.bounded.add(base)
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            targets = (
                [tgt]
                if not isinstance(tgt, ast.Tuple)
                else list(tgt.elts)
            )
            leaves = [t for t in (_leaf(x) for x in targets) if t]
            if not leaves:
                continue
            value = node.value
            if isinstance(value, ast.Call):
                name = call_name(value) or ""
                tail = name.split(".")[-1]
                if resolves_to(name, "socket.create_connection", aliases) or tail == "create_connection":
                    self.socketish.update(leaves)
                    if _has_timeout_kw(value) or (
                        len(value.args) > 1
                        and not (
                            isinstance(value.args[1], ast.Constant)
                            and value.args[1].value is None
                        )
                    ):
                        self.bounded.update(leaves)
                elif resolves_to(name, "socket.socket", aliases) or tail in (
                    "create_server",
                ):
                    self.socketish.update(leaves)
                elif tail == "accept":
                    # conn, addr = sock.accept() — first target is a socket
                    self.socketish.add(leaves[0])
                elif tail == "makefile" and isinstance(value.func, ast.Attribute):
                    src = _leaf(value.func.value)
                    if src:
                        self.socketish.update(leaves)
                        for lf in leaves:
                            makefile_edges.append((src, lf))
            elif isinstance(value, (ast.Name, ast.Attribute)):
                src = _leaf(value)
                if src:
                    for lf in leaves:
                        alias_edges.append((src, lf))
            elif isinstance(value, ast.IfExp):
                for branch in (value.body, value.orelse):
                    src = _leaf(branch)
                    if src:
                        for lf in leaves:
                            alias_edges.append((src, lf))
        # One propagation round each: facts flow through makefile() and
        # plain-alias assignments (x = slot.sock).
        for _ in range(2):
            for src, dst in makefile_edges + alias_edges:
                if src in self.socketish:
                    self.socketish.add(dst)
                if src in self.bounded:
                    self.bounded.add(dst)

    def is_socketish(self, leaf: str | None) -> bool:
        return leaf is not None and leaf in self.socketish

    def is_bounded(self, leaf: str | None) -> bool:
        return leaf is not None and leaf in self.bounded


def classify_blocking(call: ast.Call, aliases, sockets: _SocketFacts) -> str | None:
    """A human-readable description of why this call can block forever,
    or None when it is bounded/not in the blocking vocabulary."""
    name = call_name(call)
    if name is None:
        return None
    tail = name.split(".")[-1]
    receiver_leaf = None
    if isinstance(call.func, ast.Attribute):
        receiver_leaf = _leaf(call.func.value)
    if any(resolves_to(name, fn, aliases) for fn in _SUBPROCESS_FNS):
        return None if _has_timeout_kw(call) else f"{tail}() without timeout"
    if tail == "communicate":
        return None if _has_timeout_kw(call) else "communicate() without timeout"
    if tail == "get":
        if _has_timeout_kw(call):
            return None
        # block=False is non-blocking; block=True is exactly bare get()
        if any(
            kw.arg == "block"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        ):
            return None
        # get(True, t): positional timeout bounds it unless it is None
        if call.args[1:]:
            second = call.args[1]
            if not (isinstance(second, ast.Constant) and second.value is None):
                return None
        if call.args and not (
            isinstance(call.args[0], ast.Constant) and call.args[0].value is True
        ):
            return None  # dict.get(key) and friends
        if not call.args and any(
            kw.arg not in ("block", "timeout") for kw in call.keywords
        ):
            return None  # some other get(...) API, not queue.get
        return "queue.get() without timeout"
    if tail in ("join", "wait", "result"):
        if call.args or _has_timeout_kw(call):
            return None
        return f"{tail}() without timeout"
    if tail in _SOCKET_ONLY_TAILS:
        if sockets.is_bounded(receiver_leaf):
            return None
        return f"socket {tail}() with no deadline"
    if tail in _STREAM_TAILS:
        chain = name.split(".")
        piped = len(chain) >= 2 and chain[-2] in _PIPE_SEGMENTS
        if not (piped or sockets.is_socketish(receiver_leaf)):
            return None  # plain-file read: blocks on disk, not a peer
        if sockets.is_bounded(receiver_leaf):
            return None
        return f"{tail}() on a socket/pipe with no deadline"
    return None


class _ModuleLocks:
    """Lock identity resolution for one module: class catalogs (PR 13),
    annotation-typed parameters, module-level locks."""

    def __init__(self, tree: ast.AST, aliases):
        self.aliases = aliases
        self.classes = {
            n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        }
        self.class_locks = {
            name: _lock_attrs_of(node, aliases)
            for name, node in self.classes.items()
        }
        self.module_locks: set[str] = set()
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                cname = call_name(node.value)
                if cname and any(
                    resolves_to(cname, t, aliases)
                    for t in (
                        "threading.Lock",
                        "threading.RLock",
                        "threading.Condition",
                    )
                ):
                    self.module_locks.add(node.targets[0].id)

    def param_types(self, fn) -> dict[str, str]:
        out = {}
        for a in fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs:
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id in self.classes:
                out[a.arg] = ann.id
            elif (
                isinstance(ann, ast.Constant)
                and isinstance(ann.value, str)
                and ann.value in self.classes
            ):
                out[a.arg] = ann.value
        return out

    def lock_id(self, expr, owner_cls: str | None, param_types) -> str | None:
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"<module>.{expr.id}"
        chain = attr_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if len(parts) != 2:
            return None
        base, attr = parts
        if base == "self" and owner_cls is not None:
            if attr in self.class_locks.get(owner_cls, ()):
                return f"{owner_cls}.{attr}"
            return None
        cls = param_types.get(base)
        if cls is not None and attr in self.class_locks.get(cls, ()):
            return f"{cls}.{attr}"
        return None


def _own_scope_calls(fn):
    """Call nodes in ``fn``'s own scope (nested defs excluded — they run
    on their own thread/time, with their own lock state)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


class BlockingChecker:
    name = "blocking"
    rules = (RULE,)
    description = "unbounded blocking calls while a lock is held"

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            aliases = jax_aliases(tree)
            locks = _ModuleLocks(tree, aliases)
            sockets = _SocketFacts(tree, aliases)
            graph = module_call_graph(tree)
            # Pass 1: which local defs block unboundedly in their own scope
            # (lock state aside) — the one-hop composition's callee side.
            blockers: dict[str, str] = {}
            for qual, fn in graph.defs.items():
                for call in _own_scope_calls(fn):
                    why = classify_blocking(call, aliases, sockets)
                    if why is not None:
                        blockers.setdefault(qual, why)
            # Pass 2: per function, locks held at each statement.
            for qual, fn in function_defs(tree).items():
                owner_cls = qual.split(".")[0] if "." in qual else None
                findings.extend(
                    self._check_fn(
                        sf, fn, qual, owner_cls, locks, sockets, aliases,
                        graph, blockers,
                    )
                )
        return findings

    def _check_fn(self, sf, fn, qual, owner_cls, locks, sockets, aliases,
                  graph, blockers) -> list[Finding]:
        param_types = locks.param_types(fn)
        cfg = build_cfg(fn)

        def lock_of(expr):
            return locks.lock_id(expr, owner_cls, param_types)

        def gen_kill(node):
            gen, kill = [], []
            for expr in node.own_exprs():
                for call in ast.walk(expr):
                    if not isinstance(call, ast.Call) or not isinstance(
                        call.func, ast.Attribute
                    ):
                        continue
                    if call.func.attr == "acquire":
                        lid = lock_of(call.func.value)
                        if lid is not None:
                            gen.append(lid)
                    elif call.func.attr == "release":
                        lid = lock_of(call.func.value)
                        if lid is not None:
                            kill.append(lid)
            return gen, kill

        flow = forward_must(cfg, gen_kill)
        out: list[Finding] = []
        seen_lines: set[tuple[int, str]] = set()
        for node in cfg.nodes:
            with_held = [
                lid
                for lid in (lock_of(e) for e in node.with_items)
                if lid is not None
            ]
            held = sorted(set(with_held) | flow.get(node, frozenset()))
            if not held:
                continue
            # own_exprs: compound headers contribute only their header
            # expressions (bodies have their own nodes), and nested defs
            # are opaque — their bodies run without our locks.  Calls
            # under a lambda run later, elsewhere — exclude them.
            exprs = node.own_exprs()
            deferred = {
                id(sub)
                for expr in exprs
                for lam in ast.walk(expr)
                if isinstance(lam, ast.Lambda)
                for sub in ast.walk(lam.body)
            }
            for call in (
                sub
                for expr in exprs
                for sub in ast.walk(expr)
            ):
                if not isinstance(call, ast.Call) or id(call) in deferred:
                    continue
                why = classify_blocking(call, aliases, sockets)
                callee = None
                if why is None:
                    # one hop: a local function that itself blocks
                    spelling = call_name(call)
                    if spelling is not None:
                        target = graph.resolve(qual, spelling)
                        if target is not None and target in blockers:
                            callee = target
                            why = f"{target}() -> {blockers[target]}"
                if why is None:
                    continue
                key = (call.lineno, why)
                if key in seen_lines:
                    continue
                seen_lines.add(key)
                locks_s = ", ".join(held)
                out.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            f"{why} while holding {locks_s} — one slow/dead "
                            "peer wedges every thread that needs the lock "
                            "(the PR-8 router/readline class)"
                        ),
                        context=f"{qual}:{why.split('(')[0].split(' ')[-1]}:{held[0]}"
                        if callee is None
                        else f"{qual}:call:{callee}:{held[0]}",
                        fix_hint=(
                            "add a timeout/deadline, or move the wait outside "
                            "the lock (snapshot under the lock, block outside)"
                        ),
                    )
                )
        return out
