"""atomic-publish: every published file lands via tmp + ``os.replace``.

The repo's crash-consistency story (DESIGN "Checkpointing", invariants
1-7) rests on ONE idiom: write the complete payload to a sibling tmp
name, then ``os.replace`` it onto the published path — so a reader (the
serving watcher, a resuming trainer, the report tool) only ever sees a
complete file or the previous one, never a torn write.  Three historical
bugs (``kill_publish``, ``kill_writeback``, store-ahead-of-chain) were
all orderings around this idiom; this checker pins it statically:

  * **direct-write** — ``open(p, "w"/"wb")`` straight onto a published
    name.  "Published" is judged three ways: the expression is a known
    published-artifact spelling (``out_path`` / ``args.out`` /
    ``model_file`` — the committed BENCH_*/PROBE_* writers and the
    checkpoint path); its constant fragments end in ``.npz``/``.json``;
    or the same module ``os.replace``s onto that exact attribute chain
    somewhere (``self._path``).  Exempt: append modes (JSONL logs are
    append-only, not published snapshots), paths whose spelling contains
    ``tmp``, and opens whose scope later replaces that path AWAY (it IS
    the tmp).
  * **rename-no-tmp** — ``os.replace(src, dst)`` where ``src`` is a
    local built in this scope but never written here (and never handed
    to a writer call): the rename publishes bytes nobody provably wrote.
    Move-asides (``dst`` spelled ``*.corrupt``/``*.bak``/``*.tmp``) are
    quarantines, not publishes, and stay quiet.
  * **write-after-rename** — a write-open of the SAME path expression
    after the ``os.replace`` that published it, in the same scope: the
    post-rename write tears the just-published file in place.
  * **unlink-order** — a full-save scope that both unlinks the delta
    chain (``os.remove`` over ``delta_paths(...)``) and publishes must
    unlink BEFORE the rename (crash between the two leaves old-base +
    old-chain, never new-base + stale-chain — DESIGN invariant 4).

Spelling-based (``ast.unparse``) matching is deliberate: it is stable,
explainable, and matches how the publish sites are actually written;
aliased paths land in the baseline or a reasoned suppression.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    enclosing_function,
    parent_map,
)

RULE = "atomic-publish"

# Exact path-expression spellings that are published artifacts wherever
# they appear (committed probe/bench JSONs, the checkpoint path).
PUBLISHED_EXPRS = {"out_path", "args.out", "model_file"}

# Constant suffixes that mark a published name when they terminate the
# path expression's literal text.
PUBLISHED_SUFFIXES = (".npz", ".json")

# A rename TO one of these is a quarantine/move-aside, not a publish.
QUARANTINE_FRAGMENTS = (".corrupt", ".bak", ".tmp", ".quarantine")

WRITE_MODES = ("w", "wb", "w+", "wb+", "xb", "x")


def _spell(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return "<expr>"


def _open_write(call: ast.Call):
    """(path_node, mode) when ``call`` is an ``open``/``io.open`` for
    writing; None otherwise (default mode is read)."""
    name = call_name(call)
    if name not in ("open", "io.open") or not call.args:
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if not isinstance(mode, str):
        return None
    if "a" in mode or not any(mode.startswith(m) for m in WRITE_MODES):
        return None
    return call.args[0], mode


def _replace_call(call: ast.Call):
    """(src, dst) for os.replace/os.rename with two args."""
    name = call_name(call)
    if name in ("os.replace", "os.rename") and len(call.args) >= 2:
        return call.args[0], call.args[1]
    return None


def _const_text(node: ast.AST) -> str:
    """Concatenated literal fragments of a path expression — enough to
    judge tmp-ness and published suffixes on f-strings and ``+`` chains."""
    parts = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            parts.append(sub.value)
    return "".join(parts)


def _is_tmp_spelling(node: ast.AST) -> bool:
    return "tmp" in _spell(node).lower()


def _scopes(tree: ast.AST):
    """Every function body plus the module body as statement lists."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body
    yield tree, [
        s
        for s in tree.body
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
    ]


def _walk_scope_stmts(body):
    """All statements in source order, NOT descending into nested defs
    (they are their own scopes)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                yield from _walk_scope_stmts(sub)
        for h in getattr(stmt, "handlers", ()) or ():
            yield from _walk_scope_stmts(h.body)


def _stmt_expr_nodes(stmt):
    """AST nodes belonging to THIS statement only — its expression
    children, not its nested statement blocks (those are yielded as their
    own entries by ``_walk_scope_stmts``, and double-walking a ``with``
    would count its body's calls twice)."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        nodes = value if isinstance(value, list) else [value]
        for n in nodes:
            if isinstance(n, ast.AST):
                yield from ast.walk(n)


class PublishChecker:
    name = "publish"
    rules = (RULE,)
    description = "published files land via the tmp + os.replace idiom"

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            parents = parent_map(tree)
            # module-wide: attribute chains that are ever a replace DST —
            # a direct write onto one of these anywhere in the module is
            # a bypass of the module's own publish discipline.
            module_attr_dsts = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    rep = _replace_call(node)
                    if rep is not None and isinstance(rep[1], ast.Attribute):
                        chain = attr_chain(rep[1])
                        if chain:
                            module_attr_dsts.add(chain)
            for scope_node, body in _scopes(tree):
                findings.extend(
                    self._check_scope(
                        sf, scope_node, body, parents, module_attr_dsts
                    )
                )
        return findings

    def _check_scope(self, sf, scope_node, body, parents, module_attr_dsts):
        stmts = list(_walk_scope_stmts(body))
        opens = []  # (index, path_node, spell)
        replaces = []  # (index, src_node, dst_node, lineno)
        assigns = {}  # name -> index of first assignment
        arg_uses = {}  # name -> indices where passed to a non-replace call
        unlink_idx = []  # indices of chain-unlink statements
        for i, stmt in enumerate(stmts):
            for node in _stmt_expr_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                ow = _open_write(node)
                if ow is not None:
                    opens.append((i, ow[0], _spell(ow[0])))
                rep = _replace_call(node)
                if rep is not None:
                    replaces.append((i, rep[0], rep[1], node.lineno))
                else:
                    cname = call_name(node) or ""
                    if cname != "os.remove":
                        # any Name reaching a call (directly, in a list,
                        # in an f-string: subprocess argv, writer helpers)
                        # counts as handing the path to a producer
                        for arg in list(node.args) + [
                            kw.value for kw in node.keywords
                        ]:
                            for sub in ast.walk(arg):
                                if isinstance(sub, ast.Name):
                                    arg_uses.setdefault(sub.id, []).append(i)
                if (call_name(node) or "").endswith("delta_paths"):
                    unlink_idx.append(i)
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        assigns.setdefault(tgt.id, i)

        findings = []
        anchor = enclosing_function(scope_node, parents) if stmts else ""
        replaced_away = {_spell(src) for _, src, _, _ in replaces}

        # -- direct-write ------------------------------------------------
        for i, path_node, spell in opens:
            if _is_tmp_spelling(path_node) or spell in replaced_away:
                continue
            text = _const_text(path_node)
            published = (
                spell in PUBLISHED_EXPRS
                or text.endswith(PUBLISHED_SUFFIXES)
                or (isinstance(path_node, ast.Attribute) and spell in module_attr_dsts)
            )
            if not published:
                continue
            line = getattr(path_node, "lineno", 0)
            findings.append(
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=line,
                    message=(
                        f"direct write to published path {spell!r} — a crash "
                        "mid-write leaves a torn file where readers expect "
                        "complete-or-previous"
                    ),
                    context=f"{anchor}:direct:{spell}",
                    fix_hint=(
                        "write to a sibling tmp name and os.replace it onto "
                        f"{spell} (the checkpoint.py _save_npz idiom)"
                    ),
                )
            )

        # -- rename-no-tmp / write-after-rename --------------------------
        open_spells_at = [(i, spell) for i, _n, spell in opens]
        for ri, src, dst, line in replaces:
            dst_text = _const_text(dst) + _spell(dst)
            if any(frag in dst_text for frag in QUARANTINE_FRAGMENTS):
                continue  # move-aside, not a publish
            src_spell = _spell(src)
            written_before = any(
                i <= ri and spell == src_spell for i, spell in open_spells_at
            )
            if not written_before and isinstance(src, ast.Name):
                handed_off = any(
                    i <= ri for i in arg_uses.get(src.id, ())
                )
                if src.id in assigns and not handed_off:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=line,
                            message=(
                                f"os.replace publishes {src_spell!r} but this "
                                "scope never writes it (no open/writer call) "
                                "— the rename ships bytes nobody provably "
                                "produced"
                            ),
                            context=f"{anchor}:no-tmp-write:{src_spell}",
                            fix_hint=(
                                "write the tmp in the same scope (or pass it "
                                "to the writer helper) before renaming"
                            ),
                        )
                    )
            dst_spell = _spell(dst)
            for oi, spell in open_spells_at:
                if oi > ri and spell == dst_spell:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=stmts[oi].lineno,
                            message=(
                                f"write to {dst_spell!r} AFTER the os.replace "
                                f"that published it (line {line}) — tears the "
                                "published file in place"
                            ),
                            context=f"{anchor}:write-after-rename:{dst_spell}",
                            fix_hint=(
                                "fold the extra payload into the tmp before "
                                "the rename, or publish a second artifact"
                            ),
                        )
                    )

        # -- unlink-order ------------------------------------------------
        if unlink_idx and replaces:
            removes = [
                i
                for i, stmt in enumerate(stmts)
                for node in _stmt_expr_nodes(stmt)
                if isinstance(node, ast.Call)
                and (call_name(node) or "") in ("os.remove", "os.unlink")
            ]
            publish_ri = [
                ri
                for ri, _s, dst, _l in replaces
                if not any(
                    frag in (_const_text(dst) + _spell(dst))
                    for frag in QUARANTINE_FRAGMENTS
                )
            ]
            if removes and publish_ri and min(removes) > min(publish_ri):
                line = stmts[min(removes)].lineno
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=line,
                        message=(
                            "delta-chain unlink AFTER the publish rename — a "
                            "crash between the two leaves the NEW base with "
                            "the OLD chain (stale rows replay on restore); "
                            "unlink first, then rename"
                        ),
                        context=f"{anchor}:unlink-after-publish",
                        fix_hint=(
                            "order: write tmp -> unlink old deltas -> "
                            "os.replace (checkpoint.py _save_npz)"
                        ),
                    )
                )
        return findings
