"""lock-discipline / lock-order: threaded shared state, statically.

The threading surface is now ~60 primitives across 16 modules, and the
last four PRs each shipped a post-review fix for exactly this bug class
(the unlocked reload-retry flag, the stranded-pending race).  This
checker builds, per module:

  * **lock catalog** — ``self.X = threading.Lock()/RLock()/Condition()``
    assignments name the lock attributes; ``with <expr>.X`` where X is a
    known lock attribute of the base's (inferred) class counts as
    holding that lock.
  * **thread entries** — methods/nested defs handed to
    ``threading.Thread(target=...)`` / ``Timer`` / ``executor.submit``;
    when a target is a bare parameter (the ``self._spawn(fn, ...)``
    trampoline), every ``self.<method>`` passed as a call argument in
    the class becomes a potential entry.  Entries whose Thread() call
    sits in a loop/comprehension are multi-instance (N concurrent
    copies of one body).
  * **reachability** — closure over ``self.method()`` calls from each
    entry, and separately from the class's public surface ("caller"
    context: another thread is on the other end of every public method
    of these server objects).
  * **shared-mutation findings** — an attribute written OUTSIDE any
    with-lock block, reachable from a thread entry, and accessed from a
    second context (or one multi-instance entry).  ``__init__`` is
    exempt (runs before the threads exist).  Cross-object accesses
    resolve through parameter annotations (``slot: _Slot``) and
    ``self.xs = [Cls(i) ...]`` comprehensions, so Router's mutations of
    _Slot fields attribute to _Slot.
  * **lock-order graph** — edge A→B when B is acquired while A is held
    (lexically nested ``with``, or through the self-call closure);
    cycles are errors.

Guardedness is "inside ANY with-lock block" on purpose: which lock is
the *right* one is a design question the finding's fix hint hands to a
human; the checker's job is flagging mutations with no lock at all —
the historical bug class.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    jax_aliases,
    resolves_to,
)

RULE = "lock-discipline"
RULE_ORDER = "lock-order"

_LOCK_TYPES = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
)


def _is_lock_ctor(call: ast.Call, aliases) -> bool:
    name = call_name(call)
    return name is not None and any(
        resolves_to(name, t, aliases) for t in _LOCK_TYPES
    )


class _Access:
    __slots__ = ("attr", "kind", "line", "method", "guarded", "base_cls")

    def __init__(self, attr, kind, line, method, guarded, base_cls):
        self.attr = attr
        self.kind = kind  # "read" | "write"
        self.line = line
        self.method = method
        self.guarded = guarded
        self.base_cls = base_cls


class _MethodInfo:
    def __init__(self, name):
        self.name = name
        self.calls: set[str] = set()
        self.accesses: list[_Access] = []
        self.acquires: list[tuple[str, int]] = []  # (lock_id, line)
        self.calls_under: list[tuple[tuple[str, ...], str]] = []


class _ClassModel:
    def __init__(self, name, module):
        self.name = name
        self.module = module
        self.lock_attrs: set[str] = set()
        self.methods: dict[str, _MethodInfo] = {}
        self.entries: dict[str, bool] = {}  # entry -> multi-instance
        self.attr_types: dict[str, str] = {}
        self.has_dynamic_target = False
        self.method_args_passed: set[str] = set()
        self._reach = None


def _lock_attrs_of(cls: ast.ClassDef, aliases) -> set[str]:
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            tgt = node.targets[0] if len(node.targets) == 1 else None
            name = attr_chain(tgt) if tgt is not None else None
            if name and name.startswith("self.") and _is_lock_ctor(node.value, aliases):
                out.add(name.split(".", 1)[1])
    return out


class LockChecker:
    name = "locks"
    rules = (RULE, RULE_ORDER)
    description = "unguarded shared mutations + lock-order cycles"

    def __init__(self):
        self._edges: dict[tuple[str, str], tuple[str, int]] = {}

    def run(self, ctx: RepoContext) -> list[Finding]:
        self._edges = {}
        findings: list[Finding] = []
        for sf in ctx.files:
            if not sf.rel.startswith("fast_tffm_tpu/"):
                continue
            tree = sf.tree
            if tree is None:
                continue
            aliases = jax_aliases(tree)
            classes = {
                n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
            }
            lock_attrs = {
                name: _lock_attrs_of(node, aliases)
                for name, node in classes.items()
            }
            models = {
                name: self._model_class(sf, node, aliases, classes, lock_attrs)
                for name, node in classes.items()
            }
            findings.extend(self._shared_mutations(sf, models))
            self.finish_module_edges(models)
        findings.extend(self._cycles(self._edges))
        return findings

    # -- per-class modelling -------------------------------------------

    def _model_class(self, sf, cls, aliases, classes, lock_attrs) -> _ClassModel:
        model = _ClassModel(cls.name, sf.rel)
        model.lock_attrs = lock_attrs[cls.name]
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                tgt = node.targets[0] if len(node.targets) == 1 else None
                name = attr_chain(tgt) if tgt is not None else None
                if not (name and name.startswith("self.") and name.count(".") == 1):
                    continue
                attr = name.split(".", 1)[1]
                if isinstance(node.value, ast.Call):
                    cname = call_name(node.value)
                    if cname in classes:
                        model.attr_types[attr] = cname
                elif isinstance(node.value, ast.ListComp) and isinstance(
                    node.value.elt, ast.Call
                ):
                    cname = call_name(node.value.elt)
                    if cname in classes:
                        model.attr_types[attr] = cname
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._model_method(sf, model, item, aliases, classes, lock_attrs)
        if model.has_dynamic_target:
            for m in model.method_args_passed:
                if m in model.methods:
                    model.entries.setdefault(m, False)
        return model

    def _model_method(self, sf, model, fn, aliases, classes, lock_attrs):
        info = _MethodInfo(fn.name)
        model.methods[fn.name] = info
        param_types: dict[str, str] = {}
        for a in fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs:
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id in classes:
                param_types[a.arg] = ann.id
            elif (
                isinstance(ann, ast.Constant)
                and isinstance(ann.value, str)
                and ann.value in classes
            ):
                param_types[a.arg] = ann.value
        for node in ast.walk(fn):
            gens = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                gens.append((node.target, node.iter))
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                gens.extend((g.target, g.iter) for g in node.generators)
            for target, it_node in gens:
                if not isinstance(target, ast.Name):
                    continue
                for it_expr in [it_node] + (
                    list(it_node.args) if isinstance(it_node, ast.Call) else []
                ):
                    it = attr_chain(it_expr)
                    if it and it.startswith("self."):
                        t = model.attr_types.get(it.split(".", 1)[1])
                        if t:
                            param_types[target.id] = t
            # local = ClassName(...) direct construction
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                cname = call_name(node.value)
                tgt = node.targets[0] if len(node.targets) == 1 else None
                if (
                    cname in classes
                    and isinstance(tgt, ast.Name)
                ):
                    param_types[tgt.id] = cname
        nested_defs = {
            n.name
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn
        }
        st = _WalkState(
            self, sf, model, info, param_types, classes, lock_attrs, nested_defs
        )
        st.walk(fn.body, held=(), in_loop=False)

    # -- findings -------------------------------------------------------

    def _shared_mutations(self, sf, models) -> list[Finding]:
        findings = []
        per_cls: dict[str, list[tuple[_ClassModel, _Access]]] = {}
        for model in models.values():
            for info in model.methods.values():
                for acc in info.accesses:
                    per_cls.setdefault(acc.base_cls, []).append((model, acc))
        for cls_name, pairs in sorted(per_cls.items()):
            owner = models.get(cls_name)
            lock_attrs = owner.lock_attrs if owner else set()
            by_attr: dict[str, list[tuple[_ClassModel, _Access]]] = {}
            for model, acc in pairs:
                if acc.attr not in lock_attrs:
                    by_attr.setdefault(acc.attr, []).append((model, acc))
            for attr, accs in sorted(by_attr.items()):
                f = self._judge_attr(sf, cls_name, attr, accs)
                if f is not None:
                    findings.append(f)
        return findings

    def _judge_attr(self, sf, cls_name, attr, accs) -> Finding | None:
        contexts: set[str] = set()
        unguarded_writes: list[_Access] = []
        multi = False
        methods_seen = set()
        for model, acc in accs:
            if acc.method == "__init__":
                continue
            ctxs = _method_contexts(model, acc.method)
            contexts |= ctxs
            multi = multi or any(
                model.entries.get(c.split(":", 1)[1], False)
                for c in ctxs
                if c.startswith("thread:")
            )
            methods_seen.add(f"{model.name}.{acc.method}")
            if (
                acc.kind == "write"
                and not acc.guarded
                and acc.method not in _guaranteed_held(model)
            ):
                unguarded_writes.append(acc)
        if not unguarded_writes:
            return None
        thread_ctxs = {c for c in contexts if c.startswith("thread:")}
        if not thread_ctxs:
            return None
        if not (len(contexts) >= 2 or multi):
            return None
        w = unguarded_writes[0]
        return Finding(
            rule=RULE,
            path=sf.rel,
            line=w.line,
            message=(
                f"{cls_name}.{attr} is written unguarded in {w.method}() but "
                f"shared across contexts ({', '.join(sorted(contexts))}; "
                f"methods: {', '.join(sorted(methods_seen))})"
            ),
            context=f"{cls_name}.{attr}",
            severity="warning",
            fix_hint=(
                "guard every write (and compound read-modify-write) with "
                "the owning lock, or confine the attribute to one thread"
            ),
        )

    # -- lock order -----------------------------------------------------

    def add_edge(self, a, b, where):
        if a != b:
            self._edges.setdefault((a, b), where)

    def finish_module_edges(self, models):
        for model in models.values():
            all_acquires = self._transitive_acquires(model)
            for info in model.methods.values():
                for held_ids, callee in info.calls_under:
                    if not held_ids:
                        continue
                    for acq_id, line in all_acquires.get(callee, ()):
                        for h in held_ids:
                            self.add_edge(h, acq_id, (model.module, line))

    def _transitive_acquires(self, model) -> dict[str, list[tuple[str, int]]]:
        out: dict[str, list[tuple[str, int]]] = {}

        def visit(mname, seen):
            if mname in out:
                return out[mname]
            if mname in seen:
                return []
            seen.add(mname)
            info = model.methods.get(mname)
            if info is None:
                return []
            acc = list(info.acquires)
            for callee in info.calls:
                acc.extend(visit(callee, seen))
            out[mname] = acc
            return acc

        for mname in model.methods:
            visit(mname, set())
        return out

    def _cycles(self, edges) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings = []
        color: dict[str, int] = {}

        def dfs(n, stack):
            color[n] = 1
            stack.append(n)
            cyc = None
            for m in sorted(graph.get(n, ())):
                if color.get(m, 0) == 0:
                    cyc = dfs(m, stack)
                elif color.get(m) == 1:
                    cyc = stack[stack.index(m):] + [m]
                if cyc:
                    break
            stack.pop()
            color[n] = 2
            return cyc

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                cyc = dfs(n, [])
                if cyc:
                    where = edges.get((cyc[0], cyc[1])) or ("?", 0)
                    findings.append(
                        Finding(
                            rule=RULE_ORDER,
                            path=where[0],
                            line=where[1],
                            message=(
                                "lock acquisition cycle: "
                                + " -> ".join(cyc)
                                + " — two threads taking the ends in "
                                "opposite order deadlock"
                            ),
                            context="cycle:" + ">".join(sorted(set(cyc))),
                            fix_hint=(
                                "impose one global order (document it), or "
                                "release the outer lock before calling into "
                                "code that takes the inner one"
                            ),
                        )
                    )
        return findings


class _WalkState:
    """Statement walk of one method tracking held locks and loop depth.
    Nested defs are walked with the same _MethodInfo (they run with the
    method's ``self``) but inherit no held locks (they usually run later,
    on another thread)."""

    _COMPOUND = (
        ast.If, ast.For, ast.AsyncFor, ast.While, ast.Try, ast.With,
        ast.AsyncWith, ast.Match,
    )

    def __init__(self, checker, sf, model, info, param_types, classes,
                 lock_attrs, nested_defs):
        self.checker = checker
        self.sf = sf
        self.model = model
        self.info = info
        self.param_types = param_types
        self.classes = classes
        self.lock_attrs = lock_attrs
        self.nested_defs = nested_defs

    def walk(self, body, held, in_loop):
        for stmt in body:
            self.statement(stmt, held, in_loop)

    def statement(self, stmt, held, in_loop):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.walk(stmt.body, held=(), in_loop=False)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in stmt.items:
                self.scan_expr(item.context_expr, held, in_loop)
                lock_id = self._lock_id(item.context_expr)
                if lock_id is not None:
                    self.info.acquires.append((lock_id, stmt.lineno))
                    for h in new_held:
                        self.checker.add_edge(
                            h, lock_id, (self.sf.rel, stmt.lineno)
                        )
                    new_held.append(lock_id)
            self.walk(stmt.body, tuple(new_held), in_loop)
            return
        # header expressions of compound statements; whole simple ones
        if isinstance(stmt, self._COMPOUND):
            for header in self._headers(stmt):
                self.scan_expr(header, held, in_loop)
            enters_loop = isinstance(stmt, (ast.For, ast.While, ast.AsyncFor))
            for name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, name, None)
                if sub:
                    self.walk(sub, held, in_loop or enters_loop)
            for h in getattr(stmt, "handlers", ()) or ():
                self.walk(h.body, held, in_loop)
            for case in getattr(stmt, "cases", ()) or ():
                self.walk(case.body, held, in_loop)
        else:
            self.scan_expr(stmt, held, in_loop)

    @staticmethod
    def _headers(stmt):
        for field in ("test", "iter", "target", "subject"):
            v = getattr(stmt, field, None)
            if v is not None:
                yield v

    def scan_expr(self, node, held, in_loop):
        comp_calls = set()
        for sub in ast.walk(node):
            if isinstance(sub, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call):
                        comp_calls.add(id(inner))
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(sub, ast.Call):
                self._call(sub, held, in_loop or id(sub) in comp_calls)
            elif isinstance(sub, ast.Attribute):
                self._access(sub, held)

    def _call(self, call, held, in_loop):
        cname = call_name(call)
        if cname is not None and (
            cname in ("threading.Thread", "threading.Timer", "Thread", "Timer")
            or cname.endswith(".submit")
            or cname.endswith("start_new_thread")
        ):
            self._entry(call, in_loop)
        if cname and cname.startswith("self.") and cname.count(".") == 1:
            m = cname.split(".", 1)[1]
            self.info.calls.add(m)
            self.info.calls_under.append((tuple(held), m))
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            a = attr_chain(arg)
            if a and a.startswith("self.") and a.count(".") == 1:
                self.model.method_args_passed.add(a.split(".", 1)[1])

    def _entry(self, call, in_loop):
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        cname = call_name(call) or ""
        if target is None and call.args:
            if cname.endswith(".submit") or cname.endswith("start_new_thread"):
                target = call.args[0]
            elif "Timer" in cname and len(call.args) >= 2:
                target = call.args[1]
        if target is None:
            return
        t = attr_chain(target)
        if t and t.startswith("self.") and t.count(".") == 1:
            name = t.split(".", 1)[1]
            self.model.entries[name] = self.model.entries.get(name, False) or in_loop
        elif isinstance(target, ast.Name):
            if target.id in self.nested_defs:
                self.model.entries[target.id] = (
                    self.model.entries.get(target.id, False) or in_loop
                )
            else:
                self.model.has_dynamic_target = True

    def _access(self, node: ast.Attribute, held):
        if not isinstance(node.value, ast.Name):
            return
        base = node.value.id
        if base == "self":
            cls = self.model.name
        elif base in self.param_types:
            cls = self.param_types[base]
        else:
            return
        kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
        self.info.accesses.append(
            _Access(node.attr, kind, node.lineno, self.info.name, bool(held), cls)
        )

    def _lock_id(self, expr) -> str | None:
        chain = attr_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if len(parts) != 2:
            return None
        base, attr = parts
        if base == "self":
            if attr in self.model.lock_attrs:
                return f"{self.model.module}:{self.model.name}.{attr}"
            return None
        cls = self.param_types.get(base)
        if cls is not None and attr in self.lock_attrs.get(cls, ()):
            return f"{self.model.module}:{cls}.{attr}"
        return None


def _guaranteed_held(model: _ClassModel) -> set[str]:
    """Methods provably entered ONLY with a lock already held: every
    in-class call edge to them either carries a lexically-held lock or
    comes from another guaranteed method (fixed point).  Thread entries
    and the public surface are never guaranteed — an external caller
    holds nothing.  This is what lets the engine's _tick_lock-serialized
    reload tick count its callees' writes as guarded."""
    cached = getattr(model, "_guaranteed", None)
    if cached is not None:
        return cached
    edges: dict[str, list[tuple[str, bool]]] = {}
    for info in model.methods.values():
        for held_ids, callee in info.calls_under:
            edges.setdefault(callee, []).append((info.name, bool(held_ids)))
    unguardable = {m for m in model.methods if not m.startswith("_")}
    unguardable |= set(model.entries) | {"__init__"}
    guaranteed: set[str] = set()
    changed = True
    while changed:
        changed = False
        for m in model.methods:
            if m in guaranteed or m in unguardable:
                continue
            inc = edges.get(m)
            if not inc:
                continue
            if all(held or caller in guaranteed for caller, held in inc):
                guaranteed.add(m)
                changed = True
    model._guaranteed = guaranteed
    return guaranteed


def _method_contexts(model: _ClassModel, method: str) -> set[str]:
    if model._reach is None:
        reach = {}
        for entry in model.entries:
            reach[entry] = _closure(model, {entry})
        public = {m for m in model.methods if not m.startswith("_")}
        public.add("__init__")
        reach["__caller__"] = _closure(model, public)
        model._reach = reach
    out = set()
    for entry in model.entries:
        if method in model._reach[entry]:
            out.add(f"thread:{entry}")
    if method in model._reach["__caller__"]:
        out.add("caller")
    if not out:
        out.add("caller")
    return out


def _closure(model: _ClassModel, roots: set[str]) -> set[str]:
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        m = frontier.pop()
        info = model.methods.get(m)
        if info is None:
            continue
        for callee in info.calls:
            if callee not in seen:
                seen.add(callee)
                frontier.append(callee)
    return seen
