"""telemetry: RunMonitor envelope conformance (the absorbed fifth checker).

The envelope only means something if EVERY record flows through
``telemetry.RunMonitor`` and a kind registered in ``telemetry.SCHEMAS``.
RunMonitor.emit raises on unknown kinds at runtime — but only on code
paths a test actually drives; a new module quietly constructing its own
``MetricsLogger`` (or calling ``.log(kind=...)`` raw) forks the schema
without tripping anything.  The rules (unchanged from the old
tools/check_telemetry.py, now AST-resolved on the shared parse instead
of regexes, so a prose mention of ``MetricsLogger(`` in a docstring no
longer needs special-casing):

  1. ``MetricsLogger(...)`` may only be CONSTRUCTED inside the telemetry
     layer (telemetry.py owns it; utils/tracing.py defines it).
  2. Raw ``.log(kind=...)`` may only appear in the documented duck-type
     fallback (serving/metrics.py log_to) and tracing.py itself.
  3. Every string-literal kind passed to ``.emit("<kind>", ...)`` in the
     package must be registered in SCHEMAS.
"""

from __future__ import annotations

import ast
import sys

from analysis.core import Finding, RepoContext, call_name, enclosing_function, parent_map

RULE = "telemetry"

ALLOW_LOGGER_CONSTRUCTION = {
    "fast_tffm_tpu/telemetry.py",  # RunMonitor owns the logger
    "fast_tffm_tpu/utils/tracing.py",  # defines MetricsLogger
}

ALLOW_RAW_KIND_LOG = {
    "fast_tffm_tpu/utils/tracing.py",  # the logger's own implementation
    "fast_tffm_tpu/serving/metrics.py",  # documented duck-type fallback:
    #   log_to() accepts a bare MetricsLogger for envelope-less callers;
    #   every in-tree engine passes a RunMonitor (the emit() path)
}


def _default_schemas(root: str):
    if root not in sys.path:
        sys.path.insert(0, root)
    from fast_tffm_tpu.telemetry import SCHEMAS  # jax-free import

    return SCHEMAS


class TelemetryChecker:
    """``schemas`` is injectable for fixture tests; by default the real
    telemetry.SCHEMAS imports off ``ctx.root`` (telemetry.py is jax-free
    by design — PEP 562 lazy package imports, PR 4)."""

    name = "telemetry"
    rules = (RULE,)
    description = "every telemetry record rides the RunMonitor envelope"

    def __init__(self, schemas=None, package_prefix: str = "fast_tffm_tpu/"):
        self._schemas = schemas
        self._prefix = package_prefix

    def run(self, ctx: RepoContext) -> list[Finding]:
        schemas = self._schemas
        if schemas is None:
            schemas = _default_schemas(ctx.root)
        findings: list[Finding] = []
        for sf in ctx.files:
            if not sf.rel.startswith(self._prefix):
                continue
            tree = sf.tree
            if tree is None:
                continue
            parents = parent_map(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                anchor = enclosing_function(node, parents)
                if (
                    name is not None
                    and name.split(".")[-1] == "MetricsLogger"
                    and sf.rel not in ALLOW_LOGGER_CONSTRUCTION
                ):
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=node.lineno,
                            message=(
                                "MetricsLogger constructed outside the "
                                "telemetry layer — emit through a RunMonitor "
                                "(telemetry.py) so the record carries the "
                                "envelope"
                            ),
                            context=f"{anchor}:logger-construction",
                            fix_hint="build a RunMonitor (or accept one) instead",
                        )
                    )
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "log"
                    and any(kw.arg == "kind" for kw in node.keywords)
                    and sf.rel not in ALLOW_RAW_KIND_LOG
                ):
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=node.lineno,
                            message=(
                                "raw .log(kind=...) bypasses RunMonitor.emit "
                                "— the record gets no envelope and no schema "
                                "check"
                            ),
                            context=f"{anchor}:raw-log",
                            fix_hint="call monitor.emit(<kind>, ...) instead",
                        )
                    )
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    kind = node.args[0].value
                    if kind not in schemas:
                        findings.append(
                            Finding(
                                rule=RULE,
                                path=sf.rel,
                                line=node.lineno,
                                message=(
                                    f"emit of unregistered kind {kind!r} — "
                                    "register it (and its required keys) in "
                                    "telemetry.SCHEMAS"
                                ),
                                context=f"{anchor}:kind:{kind}",
                                fix_hint=(
                                    "add the kind to SCHEMAS and cover it in "
                                    "the table-driven test_telemetry suite"
                                ),
                            )
                        )
        return findings
