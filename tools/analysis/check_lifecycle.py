"""resource-lifecycle: threads, child processes, sockets/files that can
escape their owner without cleanup.

The chaos/soak harnesses and the serving fleet manage dozens of OS
resources by hand; the bugs that slipped review were all of one shape —
the cleanup exists on the happy path and is skipped on the exceptional
one (a SIGINT mid-join leaves non-daemon loadgen threads wedging
interpreter exit; a TimeoutExpired out of a cleanup ``wait(timeout=)``
leaves the child alive AND breaks the rest of the finally).  Four
sub-rules, all built on the PR-15 CFG's may-escape-without-cleanup
query plus lexical finally/handler classification:

  * **thread-never-joined** — a Thread/Timer (or a local
    ``threading.Thread`` subclass) stored on ``self`` with NO
    ``.join`` on that attribute anywhere in the class: no shutdown path
    can bound it.  Locally-created NON-daemon threads that are never
    joined in their scope are worse (they block interpreter exit) and
    read as errors.  Fire-and-forget ``daemon=True`` locals are the
    sanctioned detached idiom and stay quiet.
  * **thread-join-not-exception-safe** — non-daemon threads whose joins
    all sit on the normal path (none in a ``finally``/handler): an
    exception — including KeyboardInterrupt, the SIGINT path — between
    ``start()`` and ``join()`` abandons them and the process cannot
    exit.  Fix: ``daemon=True`` (abandonable by declaration) or join in
    a ``finally``.
  * **popen-cleanup** — a ``subprocess.Popen`` that does not escape its
    scope must reach a ``wait``/``kill``/``terminate``/``communicate``
    on every path out (CFG query), and must have one reachable on the
    EXCEPTION path (a cleanup inside ``finally``/``except``, or the
    Popen used as a context manager) — else the child outlives the
    harness.  Inside a cleanup block, ``X.wait(timeout=...)`` on a
    process that was ``terminate()``d needs a TimeoutExpired guard with
    a ``kill`` fallback: a child that ignores SIGTERM otherwise
    survives AND the raise aborts the rest of the finally.
  * **open-no-cleanup** — sockets/files opened outside ``with`` whose
    ``close`` is missing or normal-path-only while later statements can
    raise.

Escape analysis: a resource that is returned, stored into an attribute
or container, or passed to another call has transferred ownership —
the holder is responsible, not this scope.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    build_cfg,
    call_name,
    jax_aliases,
    reaches_without,
    resolves_to,
)

RULE = "resource-lifecycle"

_THREAD_CTORS = ("threading.Thread", "threading.Timer")
_POPEN_CTORS = ("subprocess.Popen",)
_SOCKET_CTORS = ("socket.socket", "socket.create_connection", "socket.create_server")
_POPEN_CLEANUP = {"wait", "kill", "terminate", "communicate"}


def _leaf(expr) -> str | None:
    chain = attr_chain(expr)
    return chain.split(".")[-1] if chain else None


def _ctor_call(value):
    """The constructor Call under an Assign value: direct, or the elt of
    a list/set comprehension / list literal (a pool of N resources)."""
    if isinstance(value, ast.Call):
        return value
    if isinstance(value, (ast.ListComp, ast.SetComp)) and isinstance(
        value.elt, ast.Call
    ):
        return value.elt
    if isinstance(value, (ast.List, ast.Set)) and value.elts and isinstance(
        value.elts[0], ast.Call
    ):
        return value.elts[0]
    return None


class _ModuleShapes:
    """Module-level facts: local Thread subclasses (and whether they
    default to daemon), import aliases."""

    def __init__(self, tree: ast.AST):
        self.aliases = jax_aliases(tree)
        self.thread_subclasses: dict[str, bool] = {}  # name -> daemon default
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = [attr_chain(b) or "" for b in node.bases]
            if not any(
                resolves_to(b, "threading.Thread", self.aliases) or b.endswith("Thread")
                for b in bases
                if b
            ):
                continue
            daemon = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    name = attr_chain(sub.targets[0]) if len(sub.targets) == 1 else None
                    if (
                        name == "self.daemon"
                        and isinstance(sub.value, ast.Constant)
                        and sub.value.value is True
                    ):
                        daemon = True
                elif isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ):
                    # super().__init__(daemon=True) — the base of the
                    # attribute is a Call, so attr_chain can't spell it
                    if sub.func.attr == "__init__":
                        for kw in sub.keywords:
                            if (
                                kw.arg == "daemon"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value is True
                            ):
                                daemon = True
            self.thread_subclasses[node.name] = daemon

    def classify_ctor(self, call: ast.Call):
        """('thread', daemon) | ('popen', None) | ('socket', None) | None"""
        name = call_name(call)
        if name is None:
            return None
        if any(resolves_to(name, t, self.aliases) for t in _THREAD_CTORS):
            return ("thread", self._daemon_kw(call))
        if name in self.thread_subclasses:
            return ("thread", self.thread_subclasses[name] or self._daemon_kw(call))
        if any(resolves_to(name, t, self.aliases) for t in _POPEN_CTORS):
            return ("popen", None)
        if any(resolves_to(name, t, self.aliases) for t in _SOCKET_CTORS):
            return ("socket", None)
        tail = name.split(".")[-1]
        if tail == "open" and name in ("open", "io.open"):
            return ("file", None)
        return None

    @staticmethod
    def _daemon_kw(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False


def _cleanup_regions(fn) -> dict[int, int]:
    """AST-node id → cleanup-region ordinal, for every node lexically
    inside a finally body or an except handler — the exception-path
    cleanup surface.  The ordinal distinguishes one try's finally from
    another's (a kill fallback in a LATER finally does not cover an
    earlier cleanup's unguarded wait)."""
    out: dict[int, int] = {}
    region = 0
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            regions = [node.finalbody] + [h.body for h in node.handlers]
            for body in regions:
                if not body:
                    continue
                region += 1
                for stmt in body:
                    for sub in ast.walk(stmt):
                        out.setdefault(id(sub), region)
    return out


def _own_scope(fn):
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _contains_owned(expr, leaf: str) -> bool:
    """Is the object named ``leaf`` handed over AS A VALUE by this
    expression — the bare name, or the name embedded in a container
    literal / constructor call?  (Mere mentions — an f-string logging
    ``proc.pid`` — are not ownership transfer.)"""
    if isinstance(expr, ast.Name):
        return expr.id == leaf
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(_contains_owned(e, leaf) for e in expr.elts)
    if isinstance(expr, ast.Dict):
        return any(
            _contains_owned(v, leaf)
            for v in list(expr.values) + [k for k in expr.keys if k is not None]
        )
    if isinstance(expr, ast.Call):
        return any(
            _contains_owned(a, leaf)
            for a in list(expr.args) + [kw.value for kw in expr.keywords]
        )
    if isinstance(expr, ast.Starred):
        return _contains_owned(expr.value, leaf)
    return False


def _escapes(fn, leaf: str, acquisition: ast.Assign) -> bool:
    """Ownership transfer: the name is returned, yielded, stored into an
    attribute/subscript/container literal or another binding, or passed
    as a call argument — the holder is responsible, not this scope."""
    for node in _own_scope(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = getattr(node, "value", None)
            if v is not None and _contains_owned(v, leaf):
                return True
        elif isinstance(node, ast.Call):
            if _contains_owned(node, leaf):
                return True
        elif isinstance(node, ast.Assign) and node is not acquisition:
            if _contains_owned(node.value, leaf):
                return True  # aliased / embedded in another value
    return False


class LifecycleChecker:
    name = "lifecycle"
    rules = (RULE,)
    description = "threads/processes/handles cleaned up on every exit path"

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            shapes = _ModuleShapes(tree)
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(sf, node, shapes))
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    findings.extend(self._check_fn(sf, node, shapes))
        return findings

    # -- class scope: self-attr resources -----------------------------------

    def _check_class(self, sf, cls, shapes) -> list[Finding]:
        findings = []
        created: dict[str, tuple[str, int]] = {}  # attr -> (kind, line)
        cleaned: set[tuple[str, str]] = set()  # (attr, tail)
        # local aliases of self-attrs, per method: `t = self._thread` then
        # `t.join()` is the attr's join (checkpoint_async's swap idiom)
        alias: dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    src = attr_chain(node.value)
                    if src and src.startswith("self.") and src.count(".") == 1:
                        alias[tgt.id] = src.split(".", 1)[1]
                name = attr_chain(tgt)
                if not (name and name.startswith("self.") and name.count(".") == 1):
                    continue
                call = _ctor_call(node.value)
                if call is None:
                    continue
                kind = shapes.classify_ctor(call)
                if kind is None:
                    continue
                if kind[0] in ("thread", "popen"):
                    created.setdefault(name.split(".", 1)[1], (kind[0], node.lineno))
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                chain = attr_chain(node.func.value)
                if chain and chain.startswith("self.") and chain.count(".") == 1:
                    cleaned.add((chain.split(".", 1)[1], node.func.attr))
                elif isinstance(node.func.value, ast.Name):
                    attr = alias.get(node.func.value.id)
                    if attr is not None:
                        cleaned.add((attr, node.func.attr))
        for attr, (kind, line) in sorted(created.items()):
            if kind == "thread" and not any(
                t == "join" for a, t in cleaned if a == attr
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=line,
                        message=(
                            f"{cls.name}.{attr} thread is started but no "
                            "method of the class ever joins it — no shutdown "
                            "path can bound its lifetime"
                        ),
                        context=f"{cls.name}.{attr}:unjoined-thread",
                        severity="warning",
                        fix_hint="join it (with a timeout) in close()/finalize()",
                    )
                )
            elif kind == "popen" and not any(
                t in _POPEN_CLEANUP for a, t in cleaned if a == attr
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=line,
                        message=(
                            f"{cls.name}.{attr} child process is never "
                            "waited/killed by any method — it outlives (or "
                            "zombifies under) the owner"
                        ),
                        context=f"{cls.name}.{attr}:unreaped-popen",
                        severity="warning",
                        fix_hint="terminate + bounded wait + kill fallback on close",
                    )
                )
        return findings

    # -- function scope: locals ---------------------------------------------

    def _check_fn(self, sf, fn, shapes) -> list[Finding]:
        findings = []
        cleanup_ids = _cleanup_regions(fn)
        acquisitions = []  # (stmt, leaf, kind, daemon)
        per_leaf_calls: dict[str, list[ast.Call]] = {}
        daemon_attr: set[str] = set()  # X.daemon = True after construction
        loop_alias: dict[str, str] = {}  # loop var -> iterated collection
        for node in _own_scope(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                it = _leaf(node.iter)
                if isinstance(node.target, ast.Name) and it is not None:
                    loop_alias[node.target.id] = it
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
            ):
                for g in node.generators:
                    it = _leaf(g.iter)
                    if isinstance(g.target, ast.Name) and it is not None:
                        loop_alias[g.target.id] = it
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                name = attr_chain(tgt)
                if (
                    name
                    and name.endswith(".daemon")
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True
                ):
                    daemon_attr.add(name.split(".")[-2])
                if not isinstance(tgt, ast.Name):
                    continue
                call = _ctor_call(node.value)
                if call is None:
                    continue
                kind = shapes.classify_ctor(call)
                if kind is not None:
                    acquisitions.append((node, tgt.id, kind[0], kind[1]))
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                leaf = _leaf(node.func.value)
                if leaf is not None:
                    per_leaf_calls.setdefault(leaf, []).append(node)
        # `for p in procs: p.wait()` — cleanup on the loop variable counts
        # for the collection it iterates.
        for var, coll in loop_alias.items():
            for c in per_leaf_calls.get(var, ()):
                per_leaf_calls.setdefault(coll, []).append(c)
        cfg = None
        for stmt, leaf, kind, daemon in acquisitions:
            if kind == "thread":
                # Joins are credited PER LEAF (loop/comprehension vars
                # alias their collection above): a join of pool `a` must
                # not excuse pool `b` in the same function.  Any receiver
                # we already know is a thread takes positional timeouts
                # too (`t.join(5.0)` — the str.join ambiguity is gone).
                leaf_joins = [
                    c
                    for c in per_leaf_calls.get(leaf, ())
                    if c.func.attr == "join"
                ]
                findings.extend(
                    self._local_thread(
                        sf, fn, stmt, leaf, daemon or leaf in daemon_attr,
                        leaf_joins, cleanup_ids,
                    )
                )
            elif kind == "popen":
                if _escapes(fn, leaf, stmt):
                    continue
                if cfg is None:
                    cfg = build_cfg(fn)
                findings.extend(
                    self._local_popen(
                        sf, fn, cfg, stmt, leaf, per_leaf_calls, cleanup_ids
                    )
                )
            elif kind in ("socket", "file"):
                if _escapes(fn, leaf, stmt):
                    continue
                findings.extend(
                    self._local_handle(
                        sf, fn, stmt, leaf, kind, per_leaf_calls, cleanup_ids
                    )
                )
        # Cleanup-block bounded waits without a TimeoutExpired guard.
        findings.extend(self._cleanup_waits(sf, fn, cleanup_ids, per_leaf_calls))
        return findings

    def _local_thread(self, sf, fn, stmt, leaf, daemon, joins, cleanup_ids):
        if not joins:
            if daemon:
                return []  # declared detached: the sanctioned idiom
            return [
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=stmt.lineno,
                    message=(
                        f"non-daemon thread(s) {leaf!r} started in "
                        f"{fn.name}() and never joined — they block "
                        "interpreter exit forever if they outlive the caller"
                    ),
                    context=f"{fn.name}:{leaf}:unjoined-thread",
                    fix_hint="join them, or pass daemon=True if abandonable",
                )
            ]
        if daemon:
            return []
        if any(id(j) in cleanup_ids for j in joins):
            return []
        return [
            Finding(
                rule=RULE,
                path=sf.rel,
                line=stmt.lineno,
                message=(
                    f"non-daemon thread(s) {leaf!r} in {fn.name}() are only "
                    "joined on the normal path — an exception (incl. "
                    "KeyboardInterrupt: the SIGINT path) between start() "
                    "and join() abandons them and the process cannot exit"
                ),
                context=f"{fn.name}:{leaf}:join-not-exception-safe",
                severity="warning",
                fix_hint=(
                    "daemon=True (abandonable by declaration) or join in a "
                    "finally"
                ),
            )
        ]

    def _local_popen(self, sf, fn, cfg, stmt, leaf, per_leaf_calls, cleanup_ids):
        cleanups = [
            c
            for c in per_leaf_calls.get(leaf, ())
            if c.func.attr in _POPEN_CLEANUP
        ]
        if not cleanups:
            return [
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=stmt.lineno,
                    message=(
                        f"Popen {leaf!r} in {fn.name}() has no reachable "
                        "wait/kill/terminate — the child outlives the harness "
                        "on every path"
                    ),
                    context=f"{fn.name}:{leaf}:unreaped-popen",
                    fix_hint="wait for it; kill in a finally on the error path",
                )
            ]
        cleanup_lines = {c.lineno for c in cleanups}

        def is_cleanup(node):
            return node.stmt is not None and any(
                isinstance(c, ast.Call)
                and c.lineno in cleanup_lines
                for c in ast.walk(node.stmt)
            )

        acq_node = cfg.by_stmt.get(stmt)
        leaky_normal = acq_node is not None and reaches_without(
            cfg, acq_node, is_cleanup
        )
        exception_safe = any(id(c) in cleanup_ids for c in cleanups)
        if exception_safe and not leaky_normal:
            return []
        if exception_safe:
            what = "a normal path leaves without wait/kill"
        elif leaky_normal:
            what = "no cleanup on the exception path (and a normal path leaks too)"
        else:
            what = (
                "no cleanup on the exception path — an exception between "
                "spawn and wait leaves the child running"
            )
        return [
            Finding(
                rule=RULE,
                path=sf.rel,
                line=stmt.lineno,
                message=f"Popen {leaf!r} in {fn.name}(): {what}",
                context=f"{fn.name}:{leaf}:popen-exception-path",
                severity="warning",
                fix_hint=(
                    "spawn inside try, terminate + bounded wait + kill "
                    "fallback in the finally"
                ),
            )
        ]

    def _local_handle(self, sf, fn, stmt, leaf, kind, per_leaf_calls, cleanup_ids):
        closes = [
            c for c in per_leaf_calls.get(leaf, ()) if c.func.attr == "close"
        ]
        if closes and any(id(c) in cleanup_ids for c in closes):
            return []
        # A handle whose whole life is the next statement or two is below
        # the noise floor only when it IS closed; unclosed is always worth
        # a finding.
        if not closes:
            return [
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=stmt.lineno,
                    message=(
                        f"{kind} {leaf!r} opened in {fn.name}() outside "
                        "with/finally and never closed in this scope"
                    ),
                    context=f"{fn.name}:{leaf}:unclosed-{kind}",
                    severity="warning",
                    fix_hint="use a with block, or close in a finally",
                )
            ]
        return [
            Finding(
                rule=RULE,
                path=sf.rel,
                line=stmt.lineno,
                message=(
                    f"{kind} {leaf!r} opened in {fn.name}() outside with/"
                    "finally — an exception before close() leaks it"
                ),
                context=f"{fn.name}:{leaf}:close-not-exception-safe",
                severity="warning",
                fix_hint="use a with block, or move close() into a finally",
            )
        ]

    def _cleanup_waits(self, sf, fn, cleanup_ids, per_leaf_calls):
        """X.wait(timeout=...) inside a finally/handler on a terminated
        process, with no TimeoutExpired guard around it."""
        findings = []
        terminated = {
            leaf
            for leaf, calls in per_leaf_calls.items()
            if any(c.func.attr in ("terminate", "kill") for c in calls)
        }
        guarded: set[int] = set()
        for node in _own_scope(fn):
            if isinstance(node, ast.Try) and node.handlers:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        guarded.add(id(sub))
        for leaf, calls in per_leaf_calls.items():
            if leaf not in terminated:
                continue
            kill_regions = {
                cleanup_ids[id(c)]
                for c in calls
                if c.func.attr == "kill" and id(c) in cleanup_ids
            }
            for c in calls:
                if c.func.attr != "wait" or id(c) not in cleanup_ids:
                    continue
                if cleanup_ids[id(c)] in kill_regions:
                    continue  # a kill fallback exists in THIS cleanup
                if not (c.args or any(kw.arg == "timeout" for kw in c.keywords)):
                    continue  # unbounded cleanup wait: bounded by design intent
                if id(c) in guarded:
                    continue
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=c.lineno,
                        message=(
                            f"cleanup does {leaf}.wait(timeout=...) after "
                            "terminate with no TimeoutExpired guard — a child "
                            "that ignores SIGTERM survives AND the raise "
                            "aborts the rest of the cleanup"
                        ),
                        context=f"{fn.name}:{leaf}:cleanup-wait-unguarded",
                        severity="warning",
                        fix_hint=(
                            "except subprocess.TimeoutExpired: proc.kill() "
                            "(then wait again)"
                        ),
                    )
                )
        return findings
