"""config-key: config.py ⇄ sample.cfg ⇄ DESIGN.md conformance.

Three artifacts describe the same key vocabulary and they drift
independently: ``config.load_config`` reads ``[Section] key`` pairs,
``sample.cfg`` documents them (active or as ``; key = value`` commented
defaults), and DESIGN.md explains them.  The rules:

  * every key read in config.py must appear in sample.cfg (same
    section) — an undocumented knob is invisible to operators;
  * every key in sample.cfg must be read by config.py — a dead key in
    the sample silently does nothing for whoever sets it (error);
  * every key read in config.py must be mentioned in DESIGN.md (the
    bare key token anywhere — DESIGN prose is not section-structured);
  * every explicit ``[Section] key`` reference in DESIGN.md must name a
    real section+key (stale design references mislead).

The reader model matches load_config's idiom exactly: section variables
(``g = "General"``) resolve through module-level assignment, and every
``get(<section>, "<key>", ...)`` call names one read.
"""

from __future__ import annotations

import ast
import os
import re

from analysis.core import Finding

RULE = "config-key"

# [Section] key references in DESIGN.md ("`[Online] follow = true`",
# "`[Checkpoint]\nfull_every_s`").  Only identifier-looking tokens with
# an underscore are treated as key references — "[General] key
# vocabulary" prose must not match.
_DESIGN_REF = re.compile(r"\[([A-Z][A-Za-z]+)\]`?\s+`?([a-z][a-z0-9_]*)")
# Active keys start the line; commented DEFAULTS are '; key = v' with the
# ';' in column 0 and one space — deeper-indented ';   word = ...' lines
# are continuation prose, not keys.
_SAMPLE_KEY = re.compile(r"^(?:([a-z][a-z0-9_]*)\s*=|; ?([a-z][a-z0-9_]*) ?=)")
_SAMPLE_SECTION = re.compile(r"^\s*\[([A-Za-z]+)\]")


def read_config_reads(config_py: str) -> dict[tuple[str, str], int]:
    """{(section, key): line} for every get(section, "key", ...) call."""
    with open(config_py, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=config_py)
    sections: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        sections[tgt.id] = node.value.value
    out: dict[tuple[str, str], int] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "get"
            and len(node.args) >= 2
        ):
            continue
        sec_node, key_node = node.args[0], node.args[1]
        if isinstance(sec_node, ast.Name):
            section = sections.get(sec_node.id)
        elif isinstance(sec_node, ast.Constant) and isinstance(sec_node.value, str):
            section = sec_node.value
        else:
            section = None
        if (
            section
            and isinstance(key_node, ast.Constant)
            and isinstance(key_node.value, str)
        ):
            out.setdefault((section, key_node.value), node.lineno)
    return out


def read_sample_keys(sample_cfg: str) -> dict[tuple[str, str], int]:
    """Documented keys: active entries AND ``; key = value`` commented
    defaults (the sample's house style annotates optional keys that
    way).  Continuation comment lines (no '=' after an identifier at
    line start) don't match."""
    out: dict[tuple[str, str], int] = {}
    section = None
    with open(sample_cfg, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            m = _SAMPLE_SECTION.match(line)
            if m:
                section = m.group(1)
                continue
            if section is None:
                continue
            m = _SAMPLE_KEY.match(line)
            if m:
                out.setdefault((section, m.group(1) or m.group(2)), i)
    return out


def read_design_refs(design_md: str):
    """(explicit [Section] key refs with lines, full text) — the text
    backs the bare-mention rule."""
    with open(design_md, encoding="utf-8") as f:
        text = f.read()
    refs: dict[tuple[str, str], int] = {}
    for m in _DESIGN_REF.finditer(text):
        key = m.group(2)
        if "_" in key:  # identifier-shaped, not prose
            refs.setdefault((m.group(1), key), text.count("\n", 0, m.start()) + 1)
    return refs, text


class ConfigChecker:
    """Paths are injectable so the fixture tests can run it against a
    synthetic trio; defaults resolve against ``ctx.root``."""

    name = "config"
    rules = (RULE,)
    description = "config.py ⇄ sample.cfg ⇄ DESIGN.md key conformance"

    def __init__(self, config_py=None, sample_cfg=None, design_md=None):
        self._config_py = config_py
        self._sample_cfg = sample_cfg
        self._design_md = design_md

    def run(self, ctx) -> list[Finding]:
        config_py = self._config_py or os.path.join(
            ctx.root, "fast_tffm_tpu", "config.py"
        )
        sample_cfg = self._sample_cfg or os.path.join(ctx.root, "sample.cfg")
        design_md = self._design_md or os.path.join(ctx.root, "DESIGN.md")
        findings: list[Finding] = []
        for path, what in ((config_py, "config.py"), (sample_cfg, "sample.cfg")):
            if not os.path.isfile(path):
                findings.append(
                    Finding(
                        rule=RULE, path=what, line=0,
                        message=f"{what} not found at {path}",
                        context=f"missing:{what}",
                    )
                )
        if findings:
            return findings
        reads = read_config_reads(config_py)
        sample = read_sample_keys(sample_cfg)
        have_design = os.path.isfile(design_md)
        design_refs, design_text = (
            read_design_refs(design_md) if have_design else ({}, "")
        )
        rel_cfg = os.path.relpath(config_py, ctx.root).replace(os.sep, "/")
        rel_sample = os.path.relpath(sample_cfg, ctx.root).replace(os.sep, "/")
        rel_design = (
            os.path.relpath(design_md, ctx.root).replace(os.sep, "/")
            if have_design
            else "DESIGN.md"
        )

        for (section, key), line in sorted(reads.items()):
            if (section, key) not in sample:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=rel_cfg,
                        line=line,
                        message=(
                            f"[{section}] {key} is read by load_config but "
                            f"absent from sample.cfg — operators cannot "
                            "discover it"
                        ),
                        context=f"undocumented:{section}.{key}",
                        fix_hint=(
                            f"add '{key} = <default>' (or the commented "
                            f"'; {key} = ...' form) under [{section}] in "
                            "sample.cfg"
                        ),
                    )
                )
            if have_design and not re.search(
                rf"\b{re.escape(key)}\b", design_text
            ):
                findings.append(
                    Finding(
                        rule=RULE,
                        path=rel_cfg,
                        line=line,
                        message=(
                            f"[{section}] {key} is read by load_config but "
                            "never mentioned in DESIGN.md"
                        ),
                        context=f"undesigned:{section}.{key}",
                        fix_hint=f"document {key} where DESIGN.md covers [{section}]",
                    )
                )
        for (section, key), line in sorted(sample.items()):
            if (section, key) not in reads:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=rel_sample,
                        line=line,
                        message=(
                            f"[{section}] {key} appears in sample.cfg but "
                            "load_config never reads it — a dead key "
                            "silently does nothing for whoever sets it"
                        ),
                        context=f"dead:{section}.{key}",
                        fix_hint="wire it into load_config or delete the sample entry",
                    )
                )
        for (section, key), line in sorted(design_refs.items()):
            if (section, key) not in reads:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=rel_design,
                        line=line,
                        message=(
                            f"DESIGN.md references [{section}] {key} but "
                            "load_config reads no such key — a stale design "
                            "reference misleads"
                        ),
                        context=f"stale-ref:{section}.{key}",
                        fix_hint="fix the section/key name or drop the reference",
                    )
                )
        return findings
