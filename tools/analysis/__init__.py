"""AST-based invariant checker suite (ISSUE 13; cross-boundary contracts ISSUE 14).

The repo's hard invariants — buffer-donation safety, zero steady-state
recompiles, lock-guarded shared state, config/schema conformance,
persisted-format stability, atomic publish discipline, exception
hygiene — are machine-checked here at commit time instead of
rediscovered in review.  ``python tools/analysis/run.py --strict`` runs
every checker over the tree and is wired into tier-1
(tests/test_analysis.py).

Modules:
  core.py             shared infra: Finding model, suppressions, baseline,
                      parsed-file cache, output rendering
  check_donation.py   donated buffers read after the donating dispatch
  check_recompile.py  jit-in-loop / uncached jit / traced Python scalars /
                      out-of-ledger .lower()/cost_analysis()
  check_locks.py      unguarded shared mutations + lock-order cycles
  check_config.py     config.py ⇄ sample.cfg ⇄ DESIGN.md key conformance
  check_telemetry.py  RunMonitor envelope conformance (absorbed from the
                      old tools/check_telemetry.py regex checker)
  check_formats.py    persisted/wire registries vs the committed
                      formats.lock.json (append-only; removal never legal)
  check_publish.py    published files land via tmp + os.replace
  check_exceptions.py bare excepts / thread-silent broad swallows /
                      diagnosis-dropping re-raises
"""
