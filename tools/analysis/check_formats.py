"""format-drift: persisted/wire registries pinned by a committed lockfile.

The repo's recurring cross-boundary bug class is FORMAT drift: a fault
kind inserted mid-tuple reshuffles every seeded chaos schedule, a renamed
npz member strands every checkpoint on disk, a reordered wire code breaks
a deployed client.  Five PRs (6, 7, 8, 11, 12) each re-verified "new
kinds append LAST" by hand; this checker turns that discipline into a
gate.  ``formats.lock.json`` (committed next to this file) pins every
persisted/wire registry:

  * ``fault_kinds``        — resilience.FAULT_KINDS (+ the SERVING/STREAM
                             subsets): ORDER is the seeded-schedule
                             contract, so the lock must be a prefix of
                             the current tuple (append-only);
  * ``telemetry_schemas``  — telemetry.SCHEMAS kinds and per-kind
                             required keys, plus ENVELOPE_FIELDS: a
                             removed kind/key orphans every committed
                             JSONL consumer;
  * ``fmb_flags``          — data/binary.py FLAG_* bit values: v2 files
                             on disk carry these bits forever;
  * ``fms_header``         — data/stream.py magic/version/header
                             layout/record geometry: append-only streams
                             outlive any one trainer;
  * ``wire_protocol``      — serving/protocol.py WIRE_CODES (ordered),
                             per-exception codes, error-response fields,
                             readiness prefixes;
  * ``checkpoint_members`` — checkpoint.py full/delta npz member names
                             and the training.py input-cursor keys +
                             version.

Judgment: a REMOVAL, REORDER, or VALUE CHANGE of anything locked is an
error — for a persisted format, removal is never legal (readers of
yesterday's bytes still exist).  An ADDITION is legal but must land with
a same-diff lockfile regeneration: ``run.py --write-lock`` (which itself
refuses to bake in a removal).  Everything is extracted from the AST —
stdlib-only, no imports of the (possibly jax-heavy) modules.
"""

from __future__ import annotations

import ast
import json
import os

from analysis.core import Finding, RepoContext

RULE = "format-drift"

LOCK_BASENAME = "formats.lock.json"

# section -> list of (entry, kind) where kind ∈ ordered | mapping | scalar.
# ``ordered`` entries are append-only sequences (lock must be a prefix of
# current); ``mapping`` entries are name->value maps whose values are
# key SETS (removal illegal, addition needs --write-lock); ``scalar``
# entries must match exactly.
SECTIONS = {
    "fault_kinds": "fast_tffm_tpu/resilience.py",
    "telemetry_schemas": "fast_tffm_tpu/telemetry.py",
    "fmb_flags": "fast_tffm_tpu/data/binary.py",
    "fms_header": "fast_tffm_tpu/data/stream.py",
    "wire_protocol": "fast_tffm_tpu/serving/protocol.py",
    "checkpoint_members": "fast_tffm_tpu/checkpoint.py",  # + training.py cursor
}


def lock_path_for(root: str) -> str:
    return os.path.join(root, "tools", "analysis", LOCK_BASENAME)


# -- AST extraction ---------------------------------------------------------


def _const_seq(node) -> list | None:
    """['kill', ...] from a Tuple/List of Constants, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for el in node.elts:
        if not isinstance(el, ast.Constant):
            return None
        v = el.value
        if isinstance(v, bytes):
            v = v.decode("latin-1")
        out.append(v)
    return out


def _module_assigns(tree: ast.AST):
    """(name, value-node) for every module-level Assign/AnnAssign."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    yield tgt.id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                yield node.target.id, node.value


def _extract_fault_kinds(tree) -> dict:
    out = {}
    for name, value in _module_assigns(tree):
        if name in ("FAULT_KINDS", "SERVING_FAULT_KINDS", "STREAM_FAULT_KINDS"):
            seq = _const_seq(value)
            if seq is not None:
                out[name] = seq
    return out


def _extract_telemetry(tree) -> dict:
    out = {}
    for name, value in _module_assigns(tree):
        if name == "ENVELOPE_FIELDS":
            seq = _const_seq(value)
            if seq is not None:
                out["ENVELOPE_FIELDS"] = seq
        elif name == "SCHEMAS" and isinstance(value, ast.Dict):
            kinds = {}
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys = _const_seq(v)
                    if keys is not None:
                        kinds[k.value] = sorted(keys)
            out["SCHEMAS"] = kinds
    return out


def _extract_fmb_flags(tree) -> dict:
    out = {}
    for name, value in _module_assigns(tree):
        if name.startswith("FLAG_") and isinstance(value, ast.Constant):
            if isinstance(value.value, int):
                out[name] = value.value
    return out


def _extract_fms_header(tree) -> dict:
    out = {}
    for name, value in _module_assigns(tree):
        if name == "FMS_MAGIC" and isinstance(value, ast.Constant):
            v = value.value
            out["magic"] = v.decode("latin-1") if isinstance(v, bytes) else v
        elif name in ("FMS_VERSION", "FMS_HEADER_BYTES"):
            if isinstance(value, ast.Constant):
                out[{"FMS_VERSION": "version", "FMS_HEADER_BYTES": "header_bytes"}[name]] = value.value
        elif name == "_HEADER" and isinstance(value, ast.Call):
            if value.args and isinstance(value.args[0], ast.Constant):
                out["struct_format"] = value.args[0].value
    # record geometry: fms_record_bytes's `A + B * int(width)` constants;
    # if the formula shape ever changes, pin its source text instead so
    # the change still reads as drift.
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "fms_record_bytes":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    expr = sub.value
                    if (
                        isinstance(expr, ast.BinOp)
                        and isinstance(expr.op, ast.Add)
                        and isinstance(expr.left, ast.Constant)
                        and isinstance(expr.right, ast.BinOp)
                        and isinstance(expr.right.op, ast.Mult)
                        and isinstance(expr.right.left, ast.Constant)
                    ):
                        out["record_bytes_fixed"] = expr.left.value
                        out["record_bytes_per_width"] = expr.right.left.value
                    else:
                        out["record_bytes_formula"] = ast.unparse(expr)
    return out


def _const_int(node) -> int | None:
    """Fold a constant int expression (handles ``1 << 24`` and friends) —
    frame geometry constants are written as shifts for readability."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.LShift):
            return left << right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Mult):
            return left * right
    return None


# Binary DATA frame constants pinned as one wire_protocol mapping: the
# header layout and kind/flag values are bytes on the wire — a deployed
# client decodes yesterday's values forever.
_FRAME_SCALARS = (
    "FRAME_MAGIC",
    "FRAME_VERSION",
    "FRAME_HEADER_FORMAT",
    "FRAME_KIND_REQUEST",
    "FRAME_KIND_SCORES",
    "FRAME_KIND_ERROR",
    "FRAME_FLAG_HAS_FIELDS",
    "FRAME_MAX_PAYLOAD",
)


def _extract_wire_protocol(tree) -> dict:
    out = {}
    codes = {}
    prefixes = {}
    frame = {}
    for name, value in _module_assigns(tree):
        if name == "WIRE_CODES":
            seq = _const_seq(value)
            if seq is not None:
                out["WIRE_CODES"] = seq
        elif name == "FRAME_STATUS_CODES":
            seq = _const_seq(value)
            if seq is not None:
                out["FRAME_STATUS_CODES"] = seq
        elif name in _FRAME_SCALARS:
            if isinstance(value, ast.Constant):
                v = value.value
                frame[name] = v.decode("latin-1") if isinstance(v, bytes) else v
            else:
                folded = _const_int(value)
                if folded is not None:
                    frame[name] = folded
        elif name.endswith("_READY_PREFIX") and isinstance(value, ast.Constant):
            prefixes[name] = value.value
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "code"
                    and isinstance(stmt.value, ast.Constant)
                ):
                    codes[node.name] = stmt.value.value
        if isinstance(node, ast.FunctionDef) and node.name == "error_response":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(sub.value, ast.Dict):
                    fields = [
                        k.value
                        for k in sub.value.keys
                        if isinstance(k, ast.Constant)
                    ]
                    out["error_response_fields"] = {f: "required" for f in fields}
    if codes:
        out["exception_codes"] = codes
    if prefixes:
        out["ready_prefixes"] = prefixes
    if frame:
        out["frame"] = frame
    return out


def _dict_member_keys(fn: ast.FunctionDef, var: str) -> list[str] | None:
    """npz member names written into ``var`` inside ``fn``: the literal
    keys of its dict construction plus every ``var["k"] = ...`` subscript
    assignment (f-string keys render as patterns: ``dense_{}``)."""
    keys: list[str] = []
    found = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == var
                    and isinstance(node.value, ast.Dict)
                ):
                    found = True
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            keys.append(k.value)
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == var
                ):
                    sl = tgt.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        keys.append(sl.value)
                    elif isinstance(sl, ast.JoinedStr):
                        pat = "".join(
                            v.value if isinstance(v, ast.Constant) else "{}"
                            for v in sl.values
                        )
                        keys.append(pat)
    return sorted(set(keys)) if found else None


def _extract_checkpoint_members(tree, training_tree=None) -> dict:
    out = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "_save_npz":
            keys = _dict_member_keys(node, "entries")
            if keys is not None:
                out["full"] = keys
        elif isinstance(node, ast.FunctionDef) and node.name == "save_delta":
            keys = _dict_member_keys(node, "entries")
            if keys is not None:
                out["delta"] = keys
    if training_tree is not None:
        for node in ast.walk(training_tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "input_cursor"
            ):
                keys = []
                version = None
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict):
                        for k, v in zip(sub.keys, sub.values):
                            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                                keys.append(k.value)
                                if k.value == "version" and isinstance(v, ast.Constant):
                                    version = v.value
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if (
                                isinstance(tgt, ast.Subscript)
                                and isinstance(tgt.slice, ast.Constant)
                                and isinstance(tgt.slice.value, str)
                            ):
                                keys.append(tgt.slice.value)
                if keys:
                    out["cursor_keys"] = sorted(set(keys))
                if version is not None:
                    out["cursor_version"] = version
    return out


def extract_registries(ctx: RepoContext) -> dict:
    """Current registry state, AST-extracted per section.  Sections whose
    source file is absent (fixture mini-repos) are simply omitted — the
    lock comparison then only judges what exists on both sides."""
    out: dict = {}
    extractors = {
        "fault_kinds": _extract_fault_kinds,
        "telemetry_schemas": _extract_telemetry,
        "fmb_flags": _extract_fmb_flags,
        "fms_header": _extract_fms_header,
        "wire_protocol": _extract_wire_protocol,
    }
    for section, rel in SECTIONS.items():
        sf = ctx.file(rel)
        if sf is None or sf.tree is None:
            continue
        if section == "checkpoint_members":
            tsf = ctx.file("fast_tffm_tpu/training.py")
            data = _extract_checkpoint_members(
                sf.tree, tsf.tree if tsf is not None else None
            )
        else:
            data = extractors[section](sf.tree)
        if data:
            out[section] = data
    return out


# -- lockfile ---------------------------------------------------------------


def load_lock(path: str) -> dict:
    """{"version": 1, "sections": {...}}; raises ValueError on any other
    shape so --write-lock (and the checker) refuse corrupt lockfiles
    loudly instead of treating them as empty."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or not isinstance(data.get("sections"), dict):
        raise ValueError(f"{path}: not a formats lockfile (no 'sections' map)")
    return data


def write_lock(path: str, sections: dict) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path + ".tmp", "w") as f:
        json.dump({"version": 1, "sections": sections}, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(path + ".tmp", path)


# Entries compared as append-only ORDERED sequences (the lock must be a
# prefix of the current value); everything else compares as a map/scalar.
_ORDERED = {
    ("fault_kinds", "FAULT_KINDS"),
    ("fault_kinds", "SERVING_FAULT_KINDS"),
    ("fault_kinds", "STREAM_FAULT_KINDS"),
    ("telemetry_schemas", "ENVELOPE_FIELDS"),
    ("wire_protocol", "WIRE_CODES"),
    ("wire_protocol", "FRAME_STATUS_CODES"),
}


def diff_lock(locked_sections: dict, current: dict):
    """(drift, additions): ``drift`` = findings-worth of removals /
    reorders / value changes (never legal), ``additions`` = entries
    present in the code but not in the lock (legal, but the lockfile must
    be regenerated in the same diff).  Each item is (section, name,
    message)."""
    drift: list[tuple[str, str, str]] = []
    additions: list[tuple[str, str, str]] = []

    def diff_value(section, name, locked, cur):
        if (section, name) in _ORDERED:
            if not isinstance(cur, list):
                drift.append((section, name, f"locked sequence became {type(cur).__name__}"))
                return
            if list(cur[: len(locked)]) != list(locked):
                # name the first divergence for the human
                for i, want in enumerate(locked):
                    got = cur[i] if i < len(cur) else "<removed>"
                    if got != want:
                        drift.append(
                            (
                                section,
                                name,
                                f"position {i} is {got!r}, locked as {want!r} "
                                "— persisted order is append-only (seeded "
                                "schedules / wire readers key on it)",
                            )
                        )
                        return
            elif len(cur) > len(locked):
                tail = cur[len(locked):]
                additions.append(
                    (section, name, f"appended {tail!r} not yet in the lockfile")
                )
        elif isinstance(locked, dict):
            if not isinstance(cur, dict):
                drift.append((section, name, f"locked mapping became {type(cur).__name__}"))
                return
            for k, lv in locked.items():
                if k not in cur:
                    drift.append(
                        (
                            section,
                            name,
                            f"{k!r} removed — readers of already-persisted "
                            "data still require it",
                        )
                    )
                elif isinstance(lv, list):
                    missing = sorted(set(lv) - set(cur[k]))
                    if missing:
                        drift.append(
                            (section, name, f"{k!r} lost required key(s) {missing}")
                        )
                    added = sorted(set(cur[k]) - set(lv))
                    if added:
                        additions.append(
                            (section, name, f"{k!r} gained key(s) {added}")
                        )
                elif cur[k] != lv:
                    drift.append(
                        (section, name, f"{k!r} changed: {lv!r} -> {cur[k]!r}")
                    )
            for k in sorted(set(cur) - set(locked)):
                additions.append((section, name, f"new entry {k!r}"))
        elif isinstance(locked, list):  # unordered member/key sets
            missing = sorted(set(locked) - set(cur or ()))
            if missing:
                drift.append(
                    (
                        section,
                        name,
                        f"removed {missing} — persisted members/keys are "
                        "forever (old files still carry them)",
                    )
                )
            added = sorted(set(cur or ()) - set(locked))
            if added:
                additions.append((section, name, f"added {added}"))
        elif cur != locked:
            drift.append((section, name, f"changed: {locked!r} -> {cur!r}"))

    for section, locked in locked_sections.items():
        if section not in current:
            if section in SECTIONS:
                drift.append(
                    (
                        section,
                        "<section>",
                        f"registry source {SECTIONS[section]} is gone or no "
                        "longer defines the locked names",
                    )
                )
            continue
        cur = current[section]
        for name, lv in locked.items():
            if name not in cur:
                drift.append((section, name, "locked registry no longer extractable"))
            else:
                diff_value(section, name, lv, cur[name])
        for name in sorted(set(cur) - set(locked)):
            additions.append((section, name, "new registry not yet locked"))
    for section in sorted(set(current) - set(locked_sections)):
        additions.append((section, "<section>", "new section not yet locked"))
    return drift, additions


class FormatsChecker:
    """``lock_path`` defaults to ``<root>/tools/analysis/formats.lock.json``
    (the committed one when root is this checkout)."""

    name = "formats"
    rules = (RULE,)
    description = "persisted/wire registries match the committed lockfile"

    def __init__(self, lock_path: str | None = None):
        self._lock_path = lock_path

    def run(self, ctx: RepoContext) -> list[Finding]:
        lock_path = self._lock_path or lock_path_for(ctx.root)
        current = extract_registries(ctx)
        rel_lock = os.path.relpath(lock_path, ctx.root).replace(os.sep, "/")
        if not os.path.isfile(lock_path):
            if not current:
                return []  # nothing lockable in this tree
            return [
                Finding(
                    rule=RULE,
                    path=rel_lock,
                    line=0,
                    message=(
                        f"no {LOCK_BASENAME} — the persisted-format registries "
                        "are unpinned; generate and commit it"
                    ),
                    context="lock:missing",
                    fix_hint="python -m tools.analysis.run --write-lock",
                )
            ]
        try:
            lock = load_lock(lock_path)
        except (ValueError, json.JSONDecodeError) as e:
            return [
                Finding(
                    rule=RULE,
                    path=rel_lock,
                    line=0,
                    message=f"lockfile unreadable: {e}",
                    context="lock:corrupt",
                    fix_hint=(
                        "restore the committed lockfile (git checkout) — do "
                        "not hand-edit it; --write-lock regenerates"
                    ),
                )
            ]
        findings = []
        drift, additions = diff_lock(lock.get("sections", {}), current)
        for section, name, msg in drift:
            src = SECTIONS.get(section, rel_lock)
            sf = ctx.file(src)
            findings.append(
                Finding(
                    rule=RULE,
                    path=src if sf is not None else rel_lock,
                    line=_anchor_line(sf, name),
                    message=f"[{section}] {name}: {msg}",
                    context=f"{section}:{name}:drift",
                    fix_hint=(
                        "removal/reorder/value-change of a persisted format "
                        "is never legal — append instead (and --write-lock); "
                        "a deliberate format break needs a version bump and "
                        "a migration story first"
                    ),
                )
            )
        for section, name, msg in additions:
            src = SECTIONS.get(section, rel_lock)
            sf = ctx.file(src)
            findings.append(
                Finding(
                    rule=RULE,
                    path=src if sf is not None else rel_lock,
                    line=_anchor_line(sf, name),
                    message=(
                        f"[{section}] {name}: {msg} — regenerate the lockfile "
                        "in this same diff"
                    ),
                    context=f"{section}:{name}:addition",
                    fix_hint="python -m tools.analysis.run --write-lock",
                )
            )
        return findings


def _anchor_line(sf, name: str) -> int:
    """Best-effort clickable line: the registry name's first definition
    line in its source file (0 when unknown)."""
    if sf is None or not name or name.startswith("<"):
        return 0
    for i, line in enumerate(sf.lines, 1):
        if line.startswith(name):  # the definition, not the __all__ entry
            return i
    for i, line in enumerate(sf.lines, 1):
        if line.lstrip().startswith(f'"{name}"'):
            return i
    return 0
