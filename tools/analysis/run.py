#!/usr/bin/env python
"""Run the invariant checker suite; gate on NEW findings.

    python tools/analysis/run.py                    # report everything
    python tools/analysis/run.py --strict           # tier-1 gate: exit 1 on
                                                    #   any finding not pinned
                                                    #   in the baseline (or a
                                                    #   baseline entry with no
                                                    #   justification)
    python tools/analysis/run.py --json out.json    # machine output (the
                                                    #   report.py Analysis
                                                    #   section's input)
    python tools/analysis/run.py --rules locks,config
    python tools/analysis/run.py --changed-only     # seconds-fast iteration
                                                    #   loop: only files
                                                    #   changed vs merge-base
                                                    #   + their importers
    python tools/analysis/run.py --write-baseline   # pin the current findings
                                                    #   (justifications still
                                                    #   owed: --strict refuses
                                                    #   empty ones)
    python tools/analysis/run.py --write-lock       # re-pin the persisted-
                                                    #   format registries into
                                                    #   formats.lock.json after
                                                    #   an APPEND (refuses
                                                    #   removals/reorders)

Exit codes: 0 = conformant; 1 = gate failed (--strict only); 2 = usage.
Stdlib-only — the suite runs where jax can't import.

Baseline policy: ``baseline.json`` (committed next to this file) pins
pre-existing findings by stable key with a WRITTEN justification each.
New findings fail --strict; paying off debt leaves stale entries the
report tells you to prune.  Per-line escapes use the suppression
comment (``# analysis: ok <rule> <reason>``) — reasons required there
too.

Lockfile policy: ``formats.lock.json`` (committed next to this file)
pins every persisted/wire registry — fault kinds, telemetry schemas,
FMB flags, FMS header, serving wire protocol, checkpoint members +
cursor.  Removal/reorder/value change is never legal (readers of
yesterday's bytes still exist); additions regenerate with --write-lock
in the same diff.  DESIGN.md "Static analysis" has the full policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_TOOLS = os.path.dirname(_HERE)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from analysis import core  # noqa: E402
from analysis import check_formats  # noqa: E402
from analysis.check_blocking import BlockingChecker  # noqa: E402
from analysis.check_collectives import CollectivesChecker  # noqa: E402
from analysis.check_config import ConfigChecker  # noqa: E402
from analysis.check_donation import DonationChecker  # noqa: E402
from analysis.check_exceptions import ExceptionChecker  # noqa: E402
from analysis.check_formats import FormatsChecker  # noqa: E402
from analysis.check_lifecycle import LifecycleChecker  # noqa: E402
from analysis.check_locks import LockChecker  # noqa: E402
from analysis.check_publish import PublishChecker  # noqa: E402
from analysis.check_recompile import RecompileChecker  # noqa: E402
from analysis.check_telemetry import TelemetryChecker  # noqa: E402

DEFAULT_BASELINE = os.path.join(_HERE, "baseline.json")

CHECKERS = {
    "donation": DonationChecker,
    "recompile": RecompileChecker,
    "locks": LockChecker,
    "config": ConfigChecker,
    "telemetry": TelemetryChecker,
    "formats": FormatsChecker,
    "publish": PublishChecker,
    "exceptions": ExceptionChecker,
    "blocking": BlockingChecker,
    "collectives": CollectivesChecker,
    "lifecycle": LifecycleChecker,
}

# Checkers that only make sense against the WHOLE tree (config's dead-key
# rule reads every get(); formats diffs every registry against the lock):
# a --changed-only subset run skips them unless one of their anchor files
# changed, in which case the full scan is the honest answer anyway.
WHOLE_REPO_RULES = {"config", "formats"}
_WHOLE_REPO_ANCHORS = (
    "fast_tffm_tpu/config.py",
    "sample.cfg",
    "DESIGN.md",
    # Every formats.lock.json registry source: editing one (e.g. a new
    # wire frame constant in serving/protocol.py) must trigger the
    # formats rule, which a --changed-only subset would otherwise skip.
    *sorted(set(check_formats.SECTIONS.values())),
    "fast_tffm_tpu/training.py",  # checkpoint_members' cursor keys
    "tools/analysis/" + check_formats.LOCK_BASENAME,
)


def _rule_prefixes(rules) -> tuple[str, ...]:
    """Baseline-key prefixes owned by the selected checkers (plus the
    framework's own suppression/parse rules, which every run produces)."""
    return tuple(
        r + "::" for name in rules for r in CHECKERS[name]().rules
    ) + ("suppression::", "parse::")


def run_suite(root: str, rules=None, ctx: core.RepoContext | None = None,
              lock_path: str | None = None):
    """(findings, ctx) over ``root`` for the named checkers (all by
    default).  Suppressions are already applied; baseline is not."""
    if ctx is None:
        ctx = core.RepoContext(root, core.discover(root))
    findings = list(ctx.parse_findings)
    for name, cls in CHECKERS.items():
        if rules and name not in rules:
            continue
        checker = cls(lock_path) if name == "formats" else cls()
        findings.extend(checker.run(ctx))
    findings = core.apply_suppressions(findings, ctx)
    core.disambiguate(findings)
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return findings, ctx


def _git_changed_rels(root: str):
    """Repo-relative paths changed vs ``git merge-base HEAD main`` plus
    staged/unstaged/untracked work — the iteration loop's diff surface.
    None (with a reason on stderr) when git cannot answer; the caller
    falls back to the full scan."""
    import subprocess

    def lines(*cmd):
        r = subprocess.run(
            ["git", *cmd], cwd=root, capture_output=True, text=True, timeout=30
        )
        if r.returncode != 0:
            raise RuntimeError(r.stderr.strip() or f"git {' '.join(cmd)} failed")
        return [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]

    try:
        # diff paths come back TOPLEVEL-relative; when --root is a
        # subdirectory of the work tree they must be rebased onto root
        # (or they never intersect discover()'s rels and the loop goes
        # silently green).
        top = os.path.abspath(lines("rev-parse", "--show-toplevel")[0])
        prefix = os.path.relpath(os.path.abspath(root), top)
        base = lines("merge-base", "HEAD", "main")[0]
        out = set(lines("diff", "--name-only", base, "HEAD"))
        out |= set(lines("diff", "--name-only"))
        out |= set(lines("diff", "--name-only", "--cached"))
        # run ls-files from the toplevel so its paths share the diff
        # paths' base and the single rebase below covers everything
        out |= {
            p.strip()
            for p in subprocess.run(
                ["git", "ls-files", "--others", "--exclude-standard"],
                cwd=top, capture_output=True, text=True, timeout=30,
            ).stdout.splitlines()
            if p.strip()
        }
        if prefix not in (".", ""):
            rebased = set()
            for p in out:
                rel = os.path.relpath(p, prefix)
                if not rel.startswith(".."):
                    rebased.add(rel.replace(os.sep, "/"))
            out = rebased
        return sorted(out)
    except (RuntimeError, OSError, subprocess.SubprocessError, IndexError) as e:
        print(f"analysis: --changed-only: git unavailable ({e}) — "
              "falling back to the full scan", file=sys.stderr)
        return None


def _module_rel_candidates(dotted: str):
    base = dotted.replace(".", "/")
    return (f"{base}.py", f"{base}/__init__.py", f"tools/{base}.py")


def _changed_closure(root: str, changed: list[str]) -> list[str]:
    """Changed analyzable files plus every module that (transitively)
    imports one of them — the set whose findings a diff can move.  Uses
    the shared parse cache, so this costs one pass over the tree."""
    all_rels = [r.replace(os.sep, "/") for r in core.discover(root)]
    all_set = set(all_rels)
    ctx = core.RepoContext(root, all_rels)
    imports: dict[str, set[str]] = {}
    import ast as _ast

    for sf in ctx.files:
        tree = sf.tree
        if tree is None:
            continue
        deps: set[str] = set()
        for node in _ast.walk(tree):
            if isinstance(node, _ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, _ast.ImportFrom) and node.module:
                names = [node.module] + [
                    f"{node.module}.{a.name}" for a in node.names
                ]
            else:
                continue
            for dotted in names:
                for cand in _module_rel_candidates(dotted):
                    if cand in all_set:
                        deps.add(cand)
        imports[sf.rel] = deps
    changed_set = {c for c in changed if c in all_set}
    # reverse-dependency fixpoint: if any dep changed, the importer is in
    selected = set(changed_set)
    grew = True
    while grew:
        grew = False
        for rel, deps in imports.items():
            if rel not in selected and deps & selected:
                selected.add(rel)
                grew = True
    return sorted(selected)


def _write_lock(root: str, lock_path: str, sections_arg: str | None) -> int:
    """Regenerate the formats lockfile (mirrors --write-baseline): refuse
    a corrupt existing lockfile (rewriting would silently launder drift),
    refuse to bake in a removal/reorder (never legal for a persisted
    format — appending is the only move), and on a --lock-sections subset
    rewrite preserve the other sections verbatim."""
    ctx = core.RepoContext(root, core.discover(root))
    current = check_formats.extract_registries(ctx)
    if not current:
        print("analysis: no lockable registries under this root", file=sys.stderr)
        return 2
    wanted = None
    if sections_arg:
        wanted = {s.strip() for s in sections_arg.split(",") if s.strip()}
        unknown = wanted - set(check_formats.SECTIONS)
        if unknown:
            print(
                f"analysis: unknown lock section(s) {sorted(unknown)} "
                f"(one of {','.join(check_formats.SECTIONS)})",
                file=sys.stderr,
            )
            return 2
    existing: dict = {}
    if os.path.isfile(lock_path):
        try:
            existing = check_formats.load_lock(lock_path).get("sections", {})
        except (ValueError, json.JSONDecodeError) as e:
            print(
                f"analysis: refusing --write-lock: existing {lock_path} is "
                f"unreadable ({e}) — restore it from git first (rewriting "
                "over corruption would silently launder any drift)",
                file=sys.stderr,
            )
            return 2
        scope = {
            k: v for k, v in existing.items() if wanted is None or k in wanted
        }
        drift, _additions = check_formats.diff_lock(scope, current)
        if drift:
            print(
                "analysis: refusing --write-lock — regeneration would bake "
                "in removals/reorders, which are never legal for a "
                "persisted format:",
                file=sys.stderr,
            )
            for section, name, msg in drift:
                print(f"  [{section}] {name}: {msg}", file=sys.stderr)
            print(
                "restore the removed entries (append-only), or — for a "
                "deliberate format break with a migration story — delete "
                "the affected section from the lockfile by hand first.",
                file=sys.stderr,
            )
            return 2
    out = dict(existing)
    for section, data in current.items():
        if wanted is None or section in wanted:
            out[section] = data
    check_formats.write_lock(lock_path, out)
    kept = sorted(set(existing) - set(current)) if wanted is None else sorted(
        set(existing) - (wanted or set())
    )
    print(
        f"analysis: locked {len(out)} section(s) into {lock_path}"
        + (f" ({len(kept)} preserved verbatim)" if kept else "")
        + " — commit it in the same diff as the registry change"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="analysis",
        description="AST invariant checkers: donation, recompile, locks, "
        "config, telemetry, formats, publish, exceptions, blocking, "
        "collectives, lifecycle.",
    )
    ap.add_argument(
        "--root",
        default=os.path.dirname(_TOOLS),
        help="repo root to analyze (default: this checkout)",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated checker subset: " + ",".join(CHECKERS),
    )
    ap.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file pinning pre-existing findings",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (every finding reads as new)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="pin the current findings into --baseline and exit",
    )
    ap.add_argument(
        "--lock",
        metavar="PATH",
        help="formats lockfile (default: <root>/tools/analysis/"
        + check_formats.LOCK_BASENAME + ")",
    )
    ap.add_argument(
        "--write-lock",
        action="store_true",
        help="regenerate the formats lockfile from the current registries "
        "and exit (refuses to bake in a removal/reorder — those are never "
        "legal for a persisted format)",
    )
    ap.add_argument(
        "--lock-sections",
        metavar="S1,S2",
        help="with --write-lock: rewrite only these sections "
        f"({','.join(check_formats.SECTIONS)}); the others are preserved "
        "verbatim",
    )
    ap.add_argument(
        "--changed-only",
        action="store_true",
        help="analyze only files changed vs `git merge-base HEAD main` "
        "(staged/unstaged/untracked included) plus every module importing "
        "them — the seconds-fast pre-commit loop.  Whole-repo rules "
        "(config, formats) are skipped unless their anchor files changed; "
        "the full scan stays the tier-1 gate",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 on new findings, unjustified baseline entries, or "
        "reason-less suppressions",
    )
    ap.add_argument("--json", metavar="PATH", help="also write machine output here ('-' = stdout)")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(CHECKERS)
        if unknown:
            print(f"analysis: unknown rule(s) {sorted(unknown)}", file=sys.stderr)
            return 2

    root = os.path.abspath(args.root)
    lock_path = args.lock or check_formats.lock_path_for(root)

    if args.lock_sections and not args.write_lock:
        print("analysis: --lock-sections requires --write-lock", file=sys.stderr)
        return 2
    if args.changed_only and args.write_baseline:
        print(
            "analysis: --changed-only cannot --write-baseline (a subset "
            "scan would erase the unscanned files' pins)",
            file=sys.stderr,
        )
        return 2
    if args.write_lock:
        return _write_lock(root, lock_path, args.lock_sections)

    changed_paths: set[str] | None = None
    ctx = None
    if args.changed_only:
        changed = _git_changed_rels(root)
        if changed is not None:
            anchors_hit = sorted(set(changed) & set(_WHOLE_REPO_ANCHORS))
            if anchors_hit:
                print(
                    f"analysis: --changed-only: anchor file(s) {anchors_hit} "
                    "changed — whole-repo rules need the full tree, running "
                    "the full scan"
                )
            else:
                selected = _changed_closure(root, changed)
                if not selected:
                    print(
                        "analysis: --changed-only: no analyzable files "
                        "changed vs merge-base — nothing to do"
                    )
                    print("analysis: OK")
                    return 0
                rules = (rules or set(CHECKERS)) - WHOLE_REPO_RULES
                if not rules:
                    # the user selected ONLY whole-repo rules: an empty
                    # set would read as "all checkers" downstream and run
                    # formats/config over a partial tree (spurious drift)
                    print(
                        "analysis: --changed-only: the selected rule(s) "
                        "are whole-repo only (config/formats) — nothing "
                        "to do; run without --changed-only"
                    )
                    print("analysis: OK")
                    return 0
                changed_paths = set(selected)
                ctx = core.RepoContext(root, selected)
                print(
                    f"analysis: --changed-only: {len(changed)} changed "
                    f"path(s) -> {len(selected)} module(s) to re-analyze "
                    f"({len(rules)} rule(s); config/formats skipped)"
                )

    findings, _ctx = run_suite(root, rules, ctx=ctx, lock_path=lock_path)

    if args.write_baseline:
        # Regeneration is non-destructive: justifications of persisting
        # pins carry over, and a --rules subset run must not erase the
        # OTHER checkers' debt — only the selected rules' pins rebuild.
        # A CORRUPT existing baseline refuses loudly: rewriting over it
        # would blank every hand-written justification with a success
        # message.
        try:
            existing = core.load_baseline(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(
                f"analysis: refusing --write-baseline: existing "
                f"{args.baseline} is unreadable ({e}) — fix or delete it "
                "first (rewriting would discard every justification)",
                file=sys.stderr,
            )
            return 2
        keep = []
        if rules is not None:
            prefixes = _rule_prefixes(rules)
            keep = [e for k, e in existing.items() if not k.startswith(prefixes)]
        just = {
            k: e.get("justification", "")
            for k, e in existing.items()
            if (e.get("justification") or "").strip()
        }
        core.write_baseline(args.baseline, findings, justifications=just,
                            keep_entries=keep)
        carried = sum(1 for f in findings if f.key in just)
        print(
            f"analysis: pinned {len(findings)} finding(s) into "
            f"{args.baseline} ({carried} justification(s) carried over, "
            f"{len(keep)} out-of-scope pin(s) preserved) — write the "
            "missing justifications (--strict refuses empty ones)"
        )
        return 0

    baseline = {} if args.no_baseline else core.load_baseline(args.baseline)
    if rules is not None:
        # a partial run must not read other checkers' pins as stale
        baseline = {
            k: v
            for k, v in baseline.items()
            if k.startswith(_rule_prefixes(rules))
        }
    if changed_paths is not None:
        # nor pins for files outside the changed closure
        baseline = {
            k: v for k, v in baseline.items() if v.get("path") in changed_paths
        }
    new, _pinned, stale = core.partition(findings, baseline)
    print(core.render_text(findings, new, stale, baseline, args.strict))

    payload = core.to_json(findings, new, stale, baseline, root)
    if args.json == "-":
        print(json.dumps(payload, indent=1, sort_keys=True))
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")

    if args.strict:
        problems = []
        if new:
            problems.append(f"{len(new)} new finding(s)")
        bad = core.unjustified(baseline)
        if bad:
            problems.append(f"{len(bad)} baseline entr(y/ies) without justification")
        if problems:
            print("analysis: GATE FAILED — " + "; ".join(problems))
            return 1
    print("analysis: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
