"""collective-divergence: SPMD uniformity of collective dispatch.

PR 7's pod rule, until now enforced only in prose: every host must
dispatch every collective/barrier in the same order, or the pod hangs
until jax's ~100 s coordination timeout (and the generation machinery
treats the survivor as wedged).  The checker machine-checks it in the
pod-executed modules: any call to a collective — ``barrier``,
``agree``, ``broadcast``, ``allgather``, ``share_cursor``,
``wait_at_barrier``, eager ``psum``/``all_gather``/``all_to_all`` — that
is CONTROL-DEPENDENT on host-varying data is an error.

Host-varying taint sources: ``process_index`` (attribute or call),
``is_lead``, ``process_identity()``, ``read_heartbeat(...)`` (per-host
liveness), plus anything assigned from them — locals within a function,
``self.X`` attributes across a class (``self._is_writer = ...is_lead``
taints every later ``if not self._is_writer:``).  Control dependence
covers the branch bodies AND the code after a host-divergent early
return (only some hosts reach it).

The sanctioned single-writer idiom (DESIGN.md invariant 6) is exactly
the pair this checker does NOT flag: ``publish_signature`` (lead-only
KV set) / ``await_signature`` (peer-only KV get) are asymmetric BY
PROTOCOL, and host-divergent *I/O* (only the lead opens the score file,
writes the sidecar, logs) is fine — divergent *dispatch* is the
deadlock.  Classes that DEFINE the collective API (a ``barrier`` or
``agree`` method) are implementation, not dispatch, and are skipped.

Second rule in the same pass: write-once KV key reuse.  The pod KV
store's keys are write-once (jax's coordination service refuses a
second set); DistributedRuntime self-namespaces with per-tag counters,
so a CONSTANT key string passed to a raw ``kv.set(...)`` from two or
more call sites is a latent second-write failure — flagged at every
site past the first.

One-hop interprocedural composition: a local function whose own body
dispatches a collective makes its call sites collective too, so
``if is_lead: self._sync_peers()`` is caught even though the barrier
lives one call away.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    module_call_graph,
)

RULE = "collective-divergence"

# Modules that execute on every pod host in lock step.
POD_MODULE_PREFIXES = (
    "fast_tffm_tpu/distributed.py",
    "fast_tffm_tpu/parallel/",
    "fast_tffm_tpu/training.py",
    "fast_tffm_tpu/checkpoint_async.py",
    "fast_tffm_tpu/checkpoint.py",
    "fast_tffm_tpu/prediction.py",
)

COLLECTIVE_TAILS = {
    "barrier",
    "agree",
    "broadcast",
    "allgather",
    "share_cursor",
    "wait_at_barrier",
    "sync_global_devices",
    "psum",
    "all_gather",
    "all_to_all",
    "pmean",
}

# The sanctioned single-writer publish pair: asymmetric by protocol.
SANCTIONED_TAILS = {"publish_signature", "await_signature"}

_TAINT_TAILS = {"process_index", "is_lead"}
_TAINT_CALLS = {"process_index", "process_identity", "read_heartbeat"}


def _defines_collective_api(cls: ast.ClassDef) -> bool:
    method_names = {
        n.name
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    return bool(method_names & {"barrier", "agree", "broadcast", "allgather"})


def _uniform_by_construction(value) -> bool:
    """A value produced BY a collective is host-uniform even when its
    arguments varied per host — ``broadcast(lead_value)`` / ``agree(x)``
    exist precisely to manufacture agreement.  Assignments from them must
    not taint the target."""
    return (
        isinstance(value, ast.Call)
        and (call_name(value) or "").split(".")[-1]
        in (COLLECTIVE_TAILS | SANCTIONED_TAILS)
    )


def _tainted_attrs(tree: ast.AST) -> dict[str, set[str]]:
    """Per class: self-attributes assigned (anywhere) from a host-varying
    expression."""
    out: dict[str, set[str]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs: set[str] = set()
        # Two passes so attr-from-attr chains settle (rare, cheap).
        for _ in range(2):
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                name = attr_chain(node.targets[0])
                if not (name and name.startswith("self.") and name.count(".") == 1):
                    continue
                if _uniform_by_construction(node.value):
                    continue
                if _taint_reason(node.value, set(), attrs) is not None:
                    attrs.add(name.split(".", 1)[1])
        out[cls.name] = attrs
    return out


def _taint_reason(expr, tainted_locals: set[str], tainted_attrs: set[str]):
    """Why ``expr`` is host-varying, or None."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            if node.id in _TAINT_TAILS or node.id in tainted_locals:
                return node.id
        elif isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain is None:
                continue
            tail = chain.split(".")[-1]
            if tail in _TAINT_TAILS:
                return chain
            if chain.startswith("self.") and chain.split(".")[1] in tainted_attrs:
                return chain
        elif isinstance(node, ast.Call):
            cname = call_name(node)
            if cname and cname.split(".")[-1] in _TAINT_CALLS:
                return f"{cname}()"
    return None


def _always_exits(body: list[ast.stmt]) -> bool:
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise))


class CollectivesChecker:
    name = "collectives"
    rules = (RULE,)
    description = "collective dispatch must be host-uniform; KV keys write-once"

    def __init__(self, module_prefixes=POD_MODULE_PREFIXES):
        self._prefixes = tuple(module_prefixes)

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            if not sf.rel.startswith(self._prefixes):
                continue
            tree = sf.tree
            if tree is None:
                continue
            findings.extend(self._check_module(sf, tree))
        return findings

    # -- divergence ---------------------------------------------------------

    def _check_module(self, sf, tree) -> list[Finding]:
        findings: list[Finding] = []
        graph = module_call_graph(tree)
        attr_taint = _tainted_attrs(tree)
        api_classes = {
            n.name
            for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef) and _defines_collective_api(n)
        }
        # One-hop callee side: local defs whose own scope dispatches a
        # collective (nested defs excluded by the call graph's own-scope
        # walk).
        collective_defs: dict[str, str] = {}
        for qual, calls in graph.calls.items():
            if qual.split(".")[0] in api_classes:
                continue
            for spelling, _call in calls:
                tail = spelling.split(".")[-1]
                if tail in COLLECTIVE_TAILS:
                    collective_defs.setdefault(qual, tail)
        for qual, fn in graph.defs.items():
            owner = qual.split(".")[0] if "." in qual else None
            if owner in api_classes:
                continue
            findings.extend(
                self._check_fn(
                    sf, fn, qual,
                    attr_taint.get(owner, set()),
                    graph, collective_defs,
                )
            )
        findings.extend(self._kv_reuse(sf, tree, api_classes))
        return findings

    def _check_fn(self, sf, fn, qual, tainted_attrs, graph, collective_defs):
        findings: list[Finding] = []
        tainted_locals: set[str] = set()
        # Locals assigned from host-varying expressions (two passes so
        # later-defined helpers assigned before use still settle).
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if (
                        isinstance(tgt, ast.Name)
                        and not _uniform_by_construction(node.value)
                        and _taint_reason(
                            node.value, tainted_locals, tainted_attrs
                        )
                    ):
                        tainted_locals.add(tgt.id)

        def reason_of(test):
            return _taint_reason(test, tainted_locals, tainted_attrs)

        def flag(call, reason, where):
            spelling = call_name(call) or "?"
            tail = spelling.split(".")[-1]
            findings.append(
                Finding(
                    rule=RULE,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f"collective {tail}() dispatched under host-varying "
                        f"control ({where} on {reason}) — hosts that skip it "
                        "desync the pod and every peer hangs in the "
                        "collective until the ~100s coordination timeout"
                    ),
                    context=f"{qual}:{tail}:{reason}",
                    fix_hint=(
                        "dispatch the collective on EVERY host (hoist it out "
                        "of the branch); keep only the I/O divergent — or, "
                        "for a true single-writer publish, use the "
                        "publish_signature/await_signature pair"
                    ),
                )
            )

        def collective_calls(stmt):
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                spelling = call_name(node)
                if spelling is None:
                    continue
                tail = spelling.split(".")[-1]
                if tail in SANCTIONED_TAILS:
                    continue
                if tail in COLLECTIVE_TAILS:
                    yield node, tail
                    continue
                target = graph.resolve(qual, spelling)
                if target is not None and target in collective_defs:
                    yield node, f"{target} -> {collective_defs[target]}"

        def walk(body, divergent):
            post_div = None  # set once a host-divergent early exit is seen
            for stmt in body:
                reason = divergent or post_div
                if isinstance(stmt, (ast.If, ast.While)):
                    r = reason_of(stmt.test)
                    inner = reason or r
                    # the header expression itself runs on every host
                    for call, _tail in collective_calls(stmt.test):
                        if reason:
                            flag(call, reason, "branch")
                    walk(stmt.body, inner)
                    walk(stmt.orelse, inner)
                    if (
                        isinstance(stmt, ast.If)
                        and r
                        and not reason
                        and _always_exits(stmt.body)
                        and not stmt.orelse
                    ):
                        post_div = r  # only some hosts execute what follows
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    r = reason_of(stmt.iter)
                    walk(stmt.body, reason or r)
                    walk(stmt.orelse, reason or r)
                    continue
                if isinstance(stmt, ast.Try):
                    walk(stmt.body, reason)
                    for h in stmt.handlers:
                        walk(h.body, reason)
                    walk(stmt.orelse, reason)
                    walk(stmt.finalbody, reason)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    walk(stmt.body, reason)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if reason:
                    for call, _tail in collective_calls(stmt):
                        flag(call, reason, "branch")

        walk(fn.body, None)
        return findings

    # -- write-once KV keys -------------------------------------------------

    def _kv_reuse(self, sf, tree, api_classes) -> list[Finding]:
        sites: dict[str, list[int]] = {}
        parents_cls: dict[int, str] = {}
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                for sub in ast.walk(cls):
                    parents_cls[id(sub)] = cls.name
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            if node.func.attr != "set" or not node.args:
                continue
            recv = attr_chain(node.func.value) or ""
            if "kv" not in recv.split(".")[-1].lower():
                continue
            if parents_cls.get(id(node)) in api_classes:
                continue  # the KV implementation / namespacing layer
            key = node.args[0]
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                sites.setdefault(key.value, []).append(node.lineno)
        findings = []
        for key, lines in sorted(sites.items()):
            for line in sorted(lines)[1:]:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=sf.rel,
                        line=line,
                        message=(
                            f"KV key {key!r} is set from {len(lines)} call "
                            "sites — pod KV keys are write-once (the second "
                            "set fails or is ignored); namespace per site "
                            "like DistributedRuntime._key does"
                        ),
                        context=f"kv-reuse:{key}",
                        severity="warning",
                        fix_hint="derive the key from a per-site tag + counter",
                    )
                )
        return findings
