"""donation-after-use: a donated buffer read after the donating dispatch.

``jit(..., donate_argnums=...)`` hands the argument's device buffer to
XLA; the Python binding still points at it, and a later read returns
garbage (or raises on newer jax) — the exact bug class the async-
checkpoint snapshot machinery exists to dodge.  The checker resolves,
per module:

  * jitted-with-donation callables — ``f = jax.jit(g, donate_argnums=
    (0,))`` / ``self._mark = jax.jit(...)`` assignments, ``@jax.jit``-
    with-donation and ``@partial(jax.jit, donate_argnums=...)``
    decorated defs (donate_argnames map to positions via the wrapped
    def's signature when it is local);
  * their call sites in the same module: any plain-name or self-attr
    argument in a donated position becomes CONSUMED after the call
    statement (unless that same statement rebinds it, the
    ``x = f(x)`` idiom);
  * any later Load of a consumed binding in the same scope, before a
    rebind/del, is a finding.  Loop bodies are walked twice so a
    loop-carried read-after-donate (consumed at the bottom, read at the
    top of the next iteration) is caught.

Interprocedural (PR 14): the module call graph (core.module_call_graph)
follows donation through ONE call boundary — a local def that passes its
own parameter into a donated position (``def save(state): _step(state)``
where ``_step`` donates arg 0) becomes a donating callable itself, so
``save(x); x.sum()`` in the same module is caught.  One hop only, no
fixpoint: a wrapper-of-a-wrapper is rare and each layer can earn its own
finding when touched.

Scope: same-module resolution only.  A factory returning a jitted
closure that another module calls is invisible here — the runtime
donation error (and the recompile sentinel's twin) covers that path.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    enclosing_function,
    jax_aliases,
    module_call_graph,
    parent_map,
    resolves_to,
)

RULE = "donation-after-use"


def _donated_positions(call: ast.Call):
    """(positions, argnames) from a jax.jit Call's keywords, or None when
    the call donates nothing."""
    pos: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                pos.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        pos.add(el.value)
        elif kw.arg == "donate_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        names.add(el.value)
    if not pos and not names:
        return None
    return pos, names


def _is_jit_call(node: ast.AST, aliases) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    return name is not None and (
        resolves_to(name, "jax.jit", aliases) or resolves_to(name, "jax.pjit", aliases)
    )


def _local_defs(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _names_to_positions(fn: ast.FunctionDef | None, names: set[str]) -> set[int]:
    if fn is None or not names:
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return {params.index(n) for n in names if n in params}


def _collect_donated(tree: ast.AST, aliases) -> dict[str, set[int]]:
    """callable name (as written at call sites: 'f' or 'self._mark')
    → donated positions."""
    defs = _local_defs(tree)
    out: dict[str, set[int]] = {}

    def positions_for(call: ast.Call, wrapped: ast.AST | None) -> set[int] | None:
        d = _donated_positions(call)
        if d is None:
            return None
        pos, names = d
        fn = None
        if isinstance(wrapped, ast.Name):
            fn = defs.get(wrapped.id)
        elif isinstance(wrapped, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = wrapped
        return pos | _names_to_positions(fn, names)

    for node in ast.walk(tree):
        # name = jax.jit(g, donate_*) / self._f = jax.jit(...)
        if isinstance(node, ast.Assign) and _is_jit_call(node.value, aliases):
            wrapped = node.value.args[0] if node.value.args else None
            pos = positions_for(node.value, wrapped)
            if pos:
                for tgt in node.targets:
                    name = attr_chain(tgt)
                    if name:
                        out[name] = pos
        # @jax.jit(donate_*) / @partial(jax.jit, donate_*) decorated defs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dname = call_name(dec)
                if dname is None:
                    continue
                if resolves_to(dname, "jax.jit", aliases):
                    pos = positions_for(dec, node)
                    if pos:
                        out[node.name] = pos
                elif resolves_to(dname, "functools.partial", aliases):
                    inner = dec.args[0] if dec.args else None
                    iname = attr_chain(inner) if inner is not None else None
                    if iname and resolves_to(iname, "jax.jit", aliases):
                        pos = positions_for(dec, node)
                        if pos:
                            out[node.name] = pos
    _propagate_through_wrappers(tree, out)
    return out


def _propagate_through_wrappers(tree: ast.AST, donated: dict[str, set[int]]) -> None:
    """ONE interprocedural hop: a local def that forwards its own
    parameter into a donated position of an already-donating callable
    donates that parameter too — registered under every spelling its
    callers use ('helper' for module-level defs, 'self.m' for methods,
    self excluded from the position count)."""
    graph = module_call_graph(tree)
    base = {k: set(v) for k, v in donated.items()}  # strictly one hop
    for qual, fn in graph.defs.items():
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        is_method = "." in qual and params[:1] == ["self"]
        for spelling, call in graph.calls.get(qual, ()):
            pos = base.get(spelling)
            if not pos:
                continue
            for i, arg in enumerate(call.args):
                if i not in pos or not isinstance(arg, ast.Name):
                    continue
                if arg.id not in params:
                    continue
                p = params.index(arg.id)
                if is_method:
                    if p == 0:
                        continue  # donating self: not expressible at call sites
                    name, cpos = f"self.{qual.split('.', 1)[1]}", p - 1
                else:
                    name, cpos = qual, p
                # never overwrite a direct-jit entry's positions; merge
                donated.setdefault(name, set()).add(cpos)


class _ScopeWalker:
    """Linear statement walk of one function body tracking consumed
    bindings.  Branch-insensitive on purpose (union semantics): an If arm
    that donates taints the fall-through — conservative, and the reason
    findings carry the donating line so a human can adjudicate fast."""

    def __init__(self, checker, donated: dict[str, set[int]], sf, parents):
        self.checker = checker
        self.donated = donated
        self.sf = sf
        self.parents = parents
        self.consumed: dict[str, tuple[str, int]] = {}  # name -> (callee, line)
        self.reported: set[tuple[int, str]] = set()

    def _donation_args(self, call: ast.Call):
        name = call_name(call)
        if name is None:
            return []
        pos = self.donated.get(name)
        if not pos:
            return []
        out = []
        for i, arg in enumerate(call.args):
            if i in pos:
                aname = attr_chain(arg)
                if aname:
                    out.append((aname, name, call.lineno))
        return out

    # -- statement walk ------------------------------------------------

    def run(self, body: list[ast.stmt]):
        self._walk_block(body)

    def _walk_block(self, body: list[ast.stmt]):
        for stmt in body:
            self._statement(stmt)

    def _statement(self, stmt: ast.stmt):
        # Nested defs/classes get their own scope (fresh walker via the
        # checker's per-function driver); don't descend here.
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return

        stores = self._store_targets(stmt)

        # 1. reads of consumed bindings anywhere in this statement
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                name = attr_chain(node)
                if name is None:
                    continue
                hit = self._consumed_hit(name)
                if hit is not None and (node.lineno, hit) not in self.reported:
                    callee, dline = self.consumed[hit]
                    self.reported.add((node.lineno, hit))
                    name = hit
                    self.checker.findings.append(
                        Finding(
                            rule=RULE,
                            path=self.sf.rel,
                            line=node.lineno,
                            message=(
                                f"{name!r} was donated to {callee!r} at line "
                                f"{dline} and read again here — the buffer "
                                "belongs to XLA after the dispatch"
                            ),
                            context=(
                                f"{enclosing_function(node, self.parents)}:{name}"
                            ),
                            fix_hint=(
                                "rebind the result (x = f(x)), device-copy "
                                "before donating (checkpoint_async."
                                "device_snapshot), or drop the donation"
                            ),
                        )
                    )

        # 2. donations performed by this statement
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                for aname, callee, line in self._donation_args(node):
                    if aname not in stores:  # x = f(x) rebinds — safe
                        self.consumed[aname] = (callee, line)

        # 3. rebinds/dels clear consumption
        for name in stores:
            self.consumed.pop(name, None)

        # recurse into compound statements in source order; loop bodies
        # run twice for the loop-carried case
        for body in self._sub_blocks(stmt):
            self._walk_block(body)
        if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
            for body in self._sub_blocks(stmt):
                self._walk_block(body)

    def _consumed_hit(self, name: str) -> str | None:
        if name in self.consumed:
            return name
        # reading THROUGH the consumed binding (x.shape, x[0] via chain)
        for c in self.consumed:
            if name.startswith(c + "."):
                return c
        return None

    @staticmethod
    def _store_targets(stmt: ast.stmt) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), (ast.Store, ast.Del)
            ):
                name = attr_chain(node)
                if name:
                    out.add(name)
        return out

    @staticmethod
    def _sub_blocks(stmt: ast.stmt):
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(stmt, field, None)
            if blk:
                yield blk
        for h in getattr(stmt, "handlers", ()) or ():
            yield h.body


class DonationChecker:
    name = "donation"
    rules = (RULE,)
    description = "donated buffers read after the donating dispatch"

    def __init__(self):
        self.findings: list[Finding] = []

    def run(self, ctx: RepoContext) -> list[Finding]:
        self.findings = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            aliases = jax_aliases(tree)
            donated = _collect_donated(tree, aliases)
            if not donated:
                continue
            parents = parent_map(tree)
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _ScopeWalker(self, donated, sf, parents).run(node.body)
            # module-level statements form one more scope
            _ScopeWalker(self, donated, sf, parents).run(
                [s for s in tree.body if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )]
            )
        return self.findings
