"""exception-hygiene: handlers that eat diagnoses the operator needed.

Three sub-rules, each distilled from a bug this repo actually shipped:

  * **bare** — ``except:`` catches SystemExit/KeyboardInterrupt and can
    wedge shutdown paths.  Always an error, everywhere.
  * **broad-swallow** — ``except Exception``/``BaseException`` in a
    THREADED module whose handler neither re-raises, nor logs/emits the
    failure, nor increments a counter, nor stashes the exception for a
    consumer.  In a thread, a swallowed exception is a silent corpse:
    the PR-6 prefetch producer used to die exactly this way and the
    trainer wedged on ``q.get()`` forever.  A module counts as threaded
    when it constructs ``threading.Thread``/``Timer``, submits to an
    executor, or spawns through a trampoline (``self._spawn``).
  * **diagnosis-dropped** — a handler that answers a caught exception by
    raising a DIFFERENT one built from constants only: no ``from``
    chain, no reference to the caught exception in the new message.  The
    PR-8 bug class: ``validate_classes``'s actionable duplicate-name
    ValueError was swallowed by a generic "bad format" re-raise.  The
    fix idiom — ``raise New(f"...: {e}") from e`` (or ``from None`` WITH
    the original text folded in, protocol.decode-style) — stays quiet.

"Logs" is judged generously (any call whose name suggests reporting:
log/emit/warn/error/print/put/set_exception/...), because the point is
not style — it is that SOME trace of the failure escapes the handler.
"""

from __future__ import annotations

import ast

from analysis.core import (
    Finding,
    RepoContext,
    attr_chain,
    call_name,
    enclosing_function,
    jax_aliases,
    parent_map,
    resolves_to,
)

RULE = "exception-hygiene"

BROAD = ("Exception", "BaseException")

# Call spellings that count as "the failure left a trace".  Matched on
# the LAST attribute segment (self._log, monitor.emit, fut.set_exception,
# stderr.write, q.put, counters.append...).
_REPORTING_TAILS = {
    "log", "emit", "warn", "warning", "error", "exception", "print",
    "put", "put_nowait", "set_exception", "append", "write", "add",
    "debug", "info", "critical", "fail", "abort",
}
_REPORTING_HEADS = {"print"}


def _is_threaded_module(tree: ast.AST, aliases) -> bool:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None:
            continue
        if (
            resolves_to(name, "threading.Thread", aliases)
            or resolves_to(name, "threading.Timer", aliases)
            or name.split(".")[-1] in ("Thread", "Timer")
            or name.endswith(".submit")
            or name.endswith("._spawn")
        ):
            return True
    return False


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare — handled separately but also broad
    names = []
    if isinstance(t, ast.Tuple):
        names = [attr_chain(el) or "" for el in t.elts]
    else:
        names = [attr_chain(t) or ""]
    return any(n.split(".")[-1] in BROAD for n in names)


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """Does anything in the handler body re-raise, log, count, or even
    LOOK AT the failure?  Referencing the bound exception counts: a
    handler that forwards ``e`` into a response/box/condition has
    consulted the diagnosis — the rule targets handlers that throw it
    away sight unseen."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.AugAssign):
            return True  # counter += 1
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is None:
                continue
            tail = name.split(".")[-1].lstrip("_")
            if tail in _REPORTING_TAILS or name in _REPORTING_HEADS:
                return True
        if (
            handler.name
            and isinstance(node, ast.Name)
            and node.id == handler.name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


def _raise_drops_diagnosis(handler: ast.ExceptHandler, node: ast.Raise) -> bool:
    """True when ``raise`` inside ``handler`` manufactures a NEW
    exception from constants only, with no chain to (or mention of) the
    caught one."""
    if node.exc is None:
        return False  # bare re-raise preserves everything
    # ``raise X from e`` chains the diagnosis (PEP 3134); ``from None``
    # only counts when the message itself folds the original in.
    if (
        isinstance(node.cause, ast.Name)
        and handler.name
        and node.cause.id == handler.name
    ):
        return False
    if not isinstance(node.exc, ast.Call):
        # re-raising the bound name (or a pre-built exc object) keeps it
        return not (
            isinstance(node.exc, ast.Name)
            and handler.name
            and node.exc.id == handler.name
        )
    if handler.name:
        # the idiom is "embed e in the new message", but a handler that
        # INSPECTED e anywhere (the PEP-562 e.name check) diagnosed it —
        # only flag handlers that never looked
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Name) and sub.id == handler.name and isinstance(sub.ctx, ast.Load):
                return False
        return True
    # no bound name at all: a constants-only re-raise cannot preserve;
    # but raising with dynamic context (locals in an f-string) is a
    # judgment call — only flag pure-constant args.
    for arg in node.exc.args:
        if not isinstance(arg, ast.Constant):
            return False
    return bool(node.exc.args)


class ExceptionChecker:
    name = "exceptions"
    rules = (RULE,)
    description = "handlers keep (or forward) the diagnosis they caught"

    def run(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            tree = sf.tree
            if tree is None:
                continue
            aliases = jax_aliases(tree)
            parents = parent_map(tree)
            threaded = _is_threaded_module(tree, aliases)
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                anchor = enclosing_function(node, parents)
                caught = (
                    "bare"
                    if node.type is None
                    else (attr_chain(node.type) or "tuple")
                )
                if node.type is None:
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=node.lineno,
                            message=(
                                "bare 'except:' — catches SystemExit/"
                                "KeyboardInterrupt and wedges shutdown; name "
                                "the exceptions this handler can actually "
                                "deal with"
                            ),
                            context=f"{anchor}:bare",
                            fix_hint="except Exception at the broadest (and report it)",
                        )
                    )
                elif threaded and _is_broad(node) and not _handler_reports(node):
                    findings.append(
                        Finding(
                            rule=RULE,
                            path=sf.rel,
                            line=node.lineno,
                            message=(
                                f"broad 'except {caught}' in a threaded "
                                "module swallows the failure without a "
                                "trace (no re-raise, no log/emit, no "
                                "counter) — a thread dying here is "
                                "invisible until something wedges"
                            ),
                            context=f"{anchor}:swallow:{caught}",
                            severity="warning",
                            fix_hint=(
                                "narrow to the exceptions this site expects, "
                                "or log/count the failure before moving on"
                            ),
                        )
                    )
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Raise) and _raise_drops_diagnosis(
                        node, sub
                    ):
                        findings.append(
                            Finding(
                                rule=RULE,
                                path=sf.rel,
                                line=sub.lineno,
                                message=(
                                    "handler replaces the caught exception "
                                    "with a generic one — the specific "
                                    "diagnosis (the PR-8 duplicate-"
                                    "serve_classes class) is lost"
                                ),
                                context=f"{anchor}:dropped",
                                severity="warning",
                                fix_hint=(
                                    "chain it (raise New(...) from e) or fold "
                                    "the original into the message "
                                    "(f'...: {e}')"
                                ),
                            )
                        )
        return findings
