#!/usr/bin/env python
"""Convert a checkpoint between the npz and orbax formats.

    python tools/convert_checkpoint.py <cfg> <src_ckpt> <dst_ckpt> [--format npz|orbax]

The config supplies the model shape (vocabulary_size, factor_num, model,
...) that sizes the state to restore into.  Restoring already handles both
formats and mesh-shape changes (checkpoint.py), so conversion is
restore → save.  Typical use: pull a pod-scale orbax directory down to a
single .npz for a one-host predict box, or seed a pod run from an npz.

Destination format defaults by suffix: a path ending in ``.orbax`` or
``/`` writes orbax, anything else npz (same rule as
``checkpoint.save_checkpoint``).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert a checkpoint between the npz and orbax formats."
    )
    ap.add_argument("config", help="INI config describing the model (see sample.cfg)")
    ap.add_argument("src", help="source checkpoint (npz file or orbax dir)")
    ap.add_argument("dst", help="destination checkpoint path")
    ap.add_argument(
        "--format",
        choices=("auto", "npz", "orbax"),
        default="auto",
        help="destination format (auto = by suffix: .orbax/trailing slash = orbax)",
    )
    args = ap.parse_args(argv)

    import jax

    from fast_tffm_tpu.checkpoint import restore_checkpoint, save_checkpoint
    from fast_tffm_tpu.config import build_model, load_config
    from fast_tffm_tpu.trainer import init_state

    cfg = load_config(args.config)
    model = build_model(cfg)
    like = init_state(
        model, jax.random.key(0), cfg.init_accumulator_value, cfg.adagrad_accumulator
    )
    state = restore_checkpoint(args.src, like)
    save_checkpoint(args.dst, state, args.format)
    print(f"converted {args.src} -> {args.dst} (step {int(state.step)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
