#!/usr/bin/env python
"""Serving load generator: drive an engine or a socket front end, emit
BENCH_SERVE JSON.

The serving analog of bench.py's train BENCH files: one JSON object with
client-observed latency percentiles (p50/p95/p99, overall AND per client
class), achieved QPS, typed-shed counts (overloaded / deadline /
unavailable), the engine's own queue/compute/occupancy metrics, and the
compile counts that pin "zero steady-state recompiles" — so future PRs
can track a serving trajectory the way BENCH_r*.json tracks training.

Two transports:

  * **in-process** (default) — a ServingEngine in this process, the
    PR-2 mode; measures the engine alone, no network.
  * **socket** (``--connect HOST:PORT`` or ``--spawn``) — speak the wire
    protocol (serving/protocol.py) to a live front end; ``--spawn``
    launches ``fast_tffm.py serve <cfg> --port 0`` itself and tears it
    down after.  ``--connections N`` pipelined TCP connections each run
    an independent open-loop schedule at qps/N — the multi-connection
    sender is what lifts the open-loop ceiling past what one
    send/recv loop can drive (the PR-2 single-loop topped out ~1k QPS).

Two modes:

  * ``open`` (default) — open-loop Poisson arrivals at ``--qps``: the
    generator submits on a fixed random schedule whether or not earlier
    requests finished, which is what exposes queueing collapse (a
    closed loop self-throttles and hides it).
  * ``closed`` — ``--concurrency`` workers each submit-and-wait in a
    loop: measures best-case service latency and saturation throughput.

Traffic shaping: ``--classes gold:0.1,std:0.9`` draws each request's
client class from the given mix (tiers come from the server's
serve_classes); ``--deadline-ms`` stamps a per-request deadline so the
deadline-shed path is exercised under load.  Request sizes are MIXED by
construction (per-line nnz drawn 1..max_nnz) so the run exercises every
ladder bucket.

Usage:
    python tools/loadgen.py run.cfg --mode open --qps 500 --duration 3
    python tools/loadgen.py run.cfg --spawn --connections 8 --qps 10000 \
        --classes gold:0.1,std:0.9 --deadline-ms 50 --out BENCH_SERVE.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from fast_tffm_tpu.serving.client import (
    FrameConnection,
    ServeConnection,
    WireRefused,
    spawn_serve,
)
from fast_tffm_tpu.serving.protocol import FRAME_HEADER, pack_request_frame


def synth_lines(cfg, n: int, max_nnz: int, seed: int) -> list[str]:
    """Random libsvm lines over the configured vocab, nnz mixed 1..max_nnz
    so the bucket ladder (and its padding) sees every width."""
    rng = np.random.default_rng(seed)
    v = min(cfg.vocabulary_size, 1 << 20)
    lines = []
    for _ in range(n):
        # Clamp to the vocab: choice(replace=False) can't draw k > v.
        k = int(rng.integers(1, min(max_nnz, v) + 1))
        ids = rng.choice(v, size=k, replace=False)
        vals = np.round(np.abs(rng.normal(size=k)) + 0.1, 4)
        toks = " ".join(f"{i}:{x}" for i, x in zip(ids, vals))
        lines.append(f"{int(rng.integers(0, 2))} {toks}")
    return lines


def parse_class_mix(spec: str) -> list[tuple[str, float]]:
    """``gold:0.1,std:0.9`` → [(name, fraction)]; fractions normalized."""
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, frac = tok.partition(":")
        if not sep or not name:
            raise ValueError(f"--classes entries are name:fraction, got {tok!r}")
        out.append((name, float(frac)))
    total = sum(f for _, f in out)
    if not out or total <= 0:
        raise ValueError(f"--classes needs positive fractions, got {spec!r}")
    return [(n, f / total) for n, f in out]


def draw_class(rng, mix: list[tuple[str, float]] | None) -> str:
    if not mix:
        return ""
    x = rng.random()
    acc = 0.0
    for name, frac in mix:
        acc += frac
        if x < acc:
            return name
    return mix[-1][0]


# ---------------------------------------------------------------------------
# result aggregation (shared by both transports)
# ---------------------------------------------------------------------------


class Results:
    """Thread-safe (klass, latency | typed code) sink."""

    def __init__(self):
        self._lock = threading.Lock()
        self.lat: list[float] = []
        self.lat_by_class: dict[str, list[float]] = {}
        self.codes: dict[str, int] = {}
        self.sent = 0

    def on_sent(self, n=1):
        with self._lock:
            self.sent += n

    def ok(self, klass: str, latency_s: float):
        with self._lock:
            self.lat.append(latency_s)
            self.lat_by_class.setdefault(klass or "default", []).append(latency_s)

    def err(self, code: str):
        with self._lock:
            self.codes[code] = self.codes.get(code, 0) + 1


def percentiles_ms(lat: list[float]) -> dict:
    if not lat:
        return {"count": 0}
    a = np.asarray(lat) * 1e3
    return {
        "count": int(a.size),
        "mean": round(float(a.mean()), 3),
        "p50": round(float(np.percentile(a, 50)), 3),
        "p95": round(float(np.percentile(a, 95)), 3),
        "p99": round(float(np.percentile(a, 99)), 3),
        "max": round(float(a.max()), 3),
    }


# ---------------------------------------------------------------------------
# in-process transport (the PR-2 path, now class/deadline aware)
# ---------------------------------------------------------------------------


def run_open_engine(engine, lines, args, mix, res: Results):
    rng = np.random.default_rng(args.seed)
    t_end = time.perf_counter() + args.duration
    i = 0
    t_next = time.perf_counter()
    while time.perf_counter() < t_end and res.sent < args.requests:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(min(t_next - now, 0.005))
            continue
        t_next += rng.exponential(1.0 / args.qps)
        klass = draw_class(rng, mix)
        t0 = time.perf_counter()
        try:
            fut = engine.submit_line(
                lines[i % len(lines)], klass=klass,
                deadline_ms=args.deadline_ms or None,
            )
        except Exception as e:
            from fast_tffm_tpu.serving.protocol import exc_code

            res.err(exc_code(e))
            res.on_sent()
            i += 1
            continue

        def _record(f, t0=t0, klass=klass):
            exc = f.exception()
            if exc is None:
                res.ok(klass, time.perf_counter() - t0)
            else:
                from fast_tffm_tpu.serving.protocol import exc_code

                res.err(exc_code(exc))

        fut.add_done_callback(_record)
        res.on_sent()
        i += 1
    # Drain: wait for stragglers to resolve (callbacks fill res).
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with res._lock:
            done = len(res.lat) + sum(res.codes.values())
        if done >= res.sent:
            break
        time.sleep(0.01)


def run_closed_engine(engine, lines, args, mix, res: Results):
    stop = time.perf_counter() + args.duration
    lock = threading.Lock()
    counter = [0]

    def worker(wid: int):
        rng = np.random.default_rng(args.seed + wid)
        i = wid
        while time.perf_counter() < stop:
            with lock:
                if counter[0] >= args.requests:
                    return
                counter[0] += 1
            klass = draw_class(rng, mix)
            t0 = time.perf_counter()
            try:
                engine.submit_line(
                    lines[i % len(lines)], klass=klass,
                    deadline_ms=args.deadline_ms or None,
                ).result(timeout=30)
            except Exception as e:
                from fast_tffm_tpu.serving.protocol import exc_code

                res.err(exc_code(e))
                res.on_sent()
                i += args.concurrency
                time.sleep(0.001)
                continue
            res.ok(klass, time.perf_counter() - t0)
            res.on_sent()
            i += args.concurrency

    threads = [
        # daemon: a SIGINT mid-run must be able to exit without joining
        # every worker (the open sockets die with the process)
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(args.concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


# ---------------------------------------------------------------------------
# socket transport (shared pipelined client: serving/client.py)
# ---------------------------------------------------------------------------


def bench_connection(port: int, host: str, res: Results) -> ServeConnection:
    """A ServeConnection routing score responses into the Results sink
    (meta = (t_send, klass)); op acks flow through request() as usual."""

    def on_response(msg, meta):
        if meta is None:
            return False  # not ours to consume
        t0, klass = meta
        if "score" in msg:
            res.ok(klass, time.perf_counter() - t0)
        else:
            res.err(msg.get("code", "unavailable"))
        return True

    return ServeConnection(port, host=host, on_response=on_response)


def send_score(conn: ServeConnection, res, line, klass, deadline_ms) -> None:
    msg = {"line": line}
    if klass:
        msg["class"] = klass
    if deadline_ms:
        msg["deadline_ms"] = deadline_ms
    conn.send(msg, meta=(time.perf_counter(), klass))
    res.on_sent()


def run_open_socket(conns: list[ServeConnection], lines, args, mix, res: Results):
    """Each connection runs an independent Poisson schedule at qps/C —
    open-loop in aggregate, parallel enough to drive 10k+ QPS from one
    Python client."""
    per_conn_qps = args.qps / len(conns)
    t_end = time.perf_counter() + args.duration
    cap = max(1, args.requests // len(conns))

    def sender(ci: int, conn: ServeConnection):
        rng = np.random.default_rng(args.seed + ci)
        i = ci
        sent = 0
        t_next = time.perf_counter()
        while time.perf_counter() < t_end and sent < cap:
            now = time.perf_counter()
            if now < t_next:
                time.sleep(min(t_next - now, 0.002))
                continue
            t_next += rng.exponential(1.0 / per_conn_qps)
            try:
                send_score(
                    conn, res, lines[i % len(lines)], draw_class(rng, mix),
                    args.deadline_ms or None,
                )
            except OSError:
                res.err("unavailable")
            sent += 1
            i += len(conns)

    threads = [
        # daemon: abandonable on SIGINT, same as the worker pools
        threading.Thread(target=sender, args=(ci, c), daemon=True)
        for ci, c in enumerate(conns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and any(c.inflight() for c in conns):
        time.sleep(0.01)


def build_frame_pool(lines, cfg, mix, rows, seed, uses_fields, deadline_ms,
                     n_templates: int = 256):
    """Pre-packed REQUEST frame templates (req_ids zeroed — the sender
    patches a fresh range in per send, one bytes-concat).  Packing lives
    HERE, outside the timed loop, so the measured client cost per frame
    is one concat + one sendall.  One class per template (drawn from the
    mix) so server-side per-class latency attribution stays exact."""
    from fast_tffm_tpu.data.libsvm import parse_lines

    pb = parse_lines(
        lines,
        vocabulary_size=cfg.vocabulary_size,
        hash_feature_id_flag=cfg.hash_feature_id,
        max_nnz=cfg.max_nnz if cfg.max_nnz > 0 else None,
    )
    rng = np.random.default_rng(seed)
    dl = np.full(rows, deadline_ms, np.float32) if deadline_ms else None
    pool = []
    for _ in range(n_templates):
        klass = draw_class(rng, mix)
        sel = rng.integers(0, pb.batch_size, size=rows)
        data = pack_request_frame(
            np.zeros(rows, np.uint32),
            pb.ids[sel],
            pb.vals[sel],
            fields=pb.fields[sel] if uses_fields else None,
            deadlines_ms=dl,
            classes=[klass] * rows if klass else None,
        )
        pool.append((data, rows, klass))
    return pool


def _frame_cb(meta: dict, res: Results):
    """Per-connection on_result sink: meta maps req_id -> (t_send, klass);
    runs on the connection's reader thread."""

    def cb(rid, status, score):
        m = meta.pop(rid, None)
        if m is None:
            return
        t0, klass = m
        if status == "ok":
            res.ok(klass, time.perf_counter() - t0)
        else:
            res.err(status)

    return cb


def run_open_frames(conns, metas, pool, rows, args, res: Results):
    """Open-loop over the binary wire: each pinned connection runs an
    independent Poisson schedule of FRAMES at (qps/C)/rows — offered load
    is still counted in requests (rows), so QPS math matches the JSONL
    path."""
    hdr = FRAME_HEADER.size
    per_conn_fps = args.qps / len(conns) / rows
    t_end = time.perf_counter() + args.duration
    cap_frames = max(1, args.requests // (len(conns) * rows))

    def sender(ci: int, conn: FrameConnection, meta: dict):
        rng = np.random.default_rng(args.seed + ci)
        rid = 1
        ti = ci
        sent = 0
        t_next = time.perf_counter()
        while time.perf_counter() < t_end and sent < cap_frames:
            now = time.perf_counter()
            if now < t_next:
                time.sleep(min(t_next - now, 0.002))
                continue
            t_next += rng.exponential(1.0 / per_conn_fps)
            data, n, klass = pool[ti % len(pool)]
            rids = np.arange(rid, rid + n, dtype=np.uint32)
            buf = data[:hdr] + rids.tobytes() + data[hdr + 4 * n:]
            t0 = time.perf_counter()
            for r in range(rid, rid + n):
                meta[r] = (t0, klass)
            try:
                conn.send_packed(buf, rids)
            except OSError:
                for r in range(rid, rid + n):
                    meta.pop(r, None)
                    res.err("unavailable")
            res.on_sent(n)
            rid += n
            sent += 1
            ti += len(conns)

    threads = [
        # daemon: abandonable on SIGINT, same as the JSONL sender pool
        threading.Thread(target=sender, args=(ci, c, m), daemon=True)
        for ci, (c, m) in enumerate(zip(conns, metas))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and any(c.inflight() for c in conns):
        time.sleep(0.01)


def drive_open(host, port, lines, cfg, args, mix, res: Results, sync=None) -> dict:
    """One process's open-loop drive: negotiate the wire (binary unless
    refused or --wire jsonl), run the schedule, drain.  ``sync`` (worker
    mode) is called after all pre-pack/connect setup and right before
    the timed loop — the multi-process start barrier.  Returns the
    transport facts + measured wall."""
    wire = args.wire
    conns: list[FrameConnection] = []
    metas: list[dict] = []
    if wire == "binary":
        try:
            for _ in range(args.connections):
                meta: dict = {}
                conns.append(
                    FrameConnection(port, host=host, on_result=_frame_cb(meta, res))
                )
                metas.append(meta)
        except WireRefused as e:
            for c in conns:
                c.close()
            conns, metas = [], []
            wire = "jsonl"
            print(f"loadgen: {e}; falling back to JSONL", file=sys.stderr)
    if wire == "binary":
        try:
            rows = max(1, min(args.frame_rows, min(c.max_frame_rows for c in conns)))
            pool = build_frame_pool(
                lines, cfg, mix, rows, args.seed, conns[0].uses_fields,
                args.deadline_ms,
            )
            if sync is not None:
                sync()
            t0 = time.perf_counter()
            run_open_frames(conns, metas, pool, rows, args, res)
            wall = time.perf_counter() - t0
            return {
                "wire": "binary",
                "frame_rows": rows,
                "client_failovers": sum(c.failovers for c in conns),
                "unanswered": sum(c.inflight() for c in conns),
                "wall": wall,
            }
        finally:
            for c in conns:
                c.close()
    jconns = [bench_connection(port, host, res) for _ in range(args.connections)]
    try:
        if sync is not None:
            sync()
        t0 = time.perf_counter()
        run_open_socket(jconns, lines, args, mix, res)
        wall = time.perf_counter() - t0
        return {
            "wire": "jsonl",
            "unanswered": sum(c.inflight() for c in jconns),
            "wall": wall,
        }
    finally:
        for c in jconns:
            c.close()


def run_worker(args, cfg, lines, mix) -> int:
    """Hidden --worker mode for --processes: drive qps/N against a LIVE
    front end, then print ONE JSON line of raw results (per-class
    latency lists in seconds) for the parent to merge.  Start barrier:
    prints WORKER_READY after setup, blocks on a stdin line."""
    host, _, port = args.connect.rpartition(":")
    host, port = host or "127.0.0.1", int(port)
    res = Results()

    def sync():
        print("WORKER_READY", flush=True)
        sys.stdin.readline()

    extra = drive_open(host, port, lines, cfg, args, mix, res, sync=sync)
    with res._lock:
        out = {
            "sent": res.sent,
            "codes": res.codes,
            "lat": {k: [round(x, 6) for x in v]
                    for k, v in res.lat_by_class.items()},
            **extra,
        }
    print(json.dumps(out, separators=(",", ":")))
    return 0


def run_multiprocess(args, host, port, res: Results) -> dict:
    """Fan the open-loop schedule across N worker PROCESSES (qps/N each)
    — one Python process tops out near ~25k offered QPS on send-side
    CPU alone; 50k+ needs real parallelism, which the GIL won't give
    threads.  Workers pre-pack, barrier on WORKER_READY/GO, then drive;
    the parent merges raw per-class latencies so percentiles are
    computed over the UNION, not averaged."""
    n = args.processes
    cmd_base = [
        sys.executable, os.path.abspath(__file__), args.config,
        "--worker", "--connect", f"{host}:{port}",
        "--mode", "open",
        "--qps", str(args.qps / n),
        "--duration", str(args.duration),
        "--connections", str(args.connections),
        "--wire", args.wire,
        "--frame-rows", str(args.frame_rows),
        "--deadline-ms", str(args.deadline_ms),
        "--requests", str(max(1, args.requests // n)),
    ]
    if args.classes:
        cmd_base += ["--classes", args.classes]
    if args.input:
        cmd_base += ["--input", args.input]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            cmd_base + ["--seed", str(args.seed + 1000 * k)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env,
        )
        for k in range(n)
    ]
    try:
        for p in procs:  # barrier: every worker finished pre-packing
            line = p.stdout.readline()
            if not line.startswith("WORKER_READY"):
                raise RuntimeError(f"worker died during setup: {line!r}")
        for p in procs:  # fire together
            p.stdin.write("GO\n")
            p.stdin.flush()
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=args.duration + 300)
            if p.returncode != 0:
                raise RuntimeError(f"worker exited rc={p.returncode}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for o in outs:
        res.sent += o["sent"]
        for code, c in o["codes"].items():
            res.codes[code] = res.codes.get(code, 0) + c
        for klass, lat in o["lat"].items():
            res.lat.extend(lat)
            res.lat_by_class.setdefault(klass, []).extend(lat)
    return {
        "processes": n,
        "wire": outs[0].get("wire"),
        "frame_rows": outs[0].get("frame_rows"),
        "client_failovers": sum(o.get("client_failovers", 0) for o in outs),
        "unanswered": sum(o["unanswered"] for o in outs),
        "wall": max(o["wall"] for o in outs),
    }


def run_closed_socket(port, host, lines, args, mix, res: Results):
    stop = time.perf_counter() + args.duration
    lock = threading.Lock()
    counter = [0]

    def worker(wid: int):
        conn = ServeConnection(port, host=host)
        rng = np.random.default_rng(args.seed + wid)
        i = wid
        try:
            while time.perf_counter() < stop:
                with lock:
                    if counter[0] >= args.requests:
                        return
                    counter[0] += 1
                klass = draw_class(rng, mix)
                t0 = time.perf_counter()
                try:
                    msg = conn.request(
                        {
                            "line": lines[i % len(lines)],
                            **({"class": klass} if klass else {}),
                            **(
                                {"deadline_ms": args.deadline_ms}
                                if args.deadline_ms
                                else {}
                            ),
                        },
                        timeout=30,
                    )
                except (TimeoutError, OSError):
                    res.err("unavailable")
                    res.on_sent()
                    i += args.concurrency
                    continue
                res.on_sent()
                if "score" in msg:
                    res.ok(klass, time.perf_counter() - t0)
                else:
                    res.err(msg.get("code", "unavailable"))
                i += args.concurrency
        finally:
            conn.close()

    threads = [
        # daemon: a SIGINT mid-run must be able to exit without joining
        # every worker (the open sockets die with the process)
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(args.concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_freshness_probe(args, cfg, log) -> int:
    """Tagged-probe freshness SLO, measured BLACK-BOX through the socket
    front end (ISSUE 9): per trial, score a sentinel id, atomically
    publish a checkpoint whose sentinel row changed, then poll the
    sentinel through the wire until its score flips.  flip-time − publish
    -time IS publish→first-scored-with-new-rows as a client experiences
    it — router reload poll, restore, collector swap, and micro-batch
    flush all included.  The server-side kind=freshness records (engine +
    router) measure the same pipe white-box; the probe JSON carries both,
    stamped with the tier's run_id so it joins the telemetry streams."""
    import jax

    from fast_tffm_tpu.checkpoint import restore_checkpoint, save_checkpoint
    from fast_tffm_tpu.config import build_model
    from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact
    from fast_tffm_tpu.trainer import init_state

    if cfg.serve_reload_interval_s <= 0:
        print(
            "probe-freshness: [Serving] reload_interval_s must be > 0 "
            "(the router's checkpoint watcher drives the reload fan-out)",
            file=sys.stderr,
        )
        return 2
    model = build_model(cfg)
    state = init_state(
        model, jax.random.key(args.seed), cfg.init_accumulator_value,
        cfg.adagrad_accumulator,
    )
    if os.path.exists(cfg.model_file.rstrip("/")):
        state = restore_checkpoint(cfg.model_file, state)
    else:
        save_checkpoint(cfg.model_file, state)
        log(f"probe-freshness: wrote fresh checkpoint {cfg.model_file}")
    sentinel = 1  # any in-vocab id works; the probe only needs its row
    line = f"0 {sentinel}:1.0"
    proc, port = spawn_serve(args.config, log=log)
    conn = ServeConnection(port)
    flips_ms: list[float] = []
    unanswered = 0
    try:
        for trial in range(args.probe_freshness):
            s0 = float(conn.request({"line": line}, timeout=30)["score"])
            # Perturb the sentinel row (bias + factors) and publish — the
            # atomic tmp+rename the trainer's saves use, so the tier sees
            # exactly a production publish.
            state = state._replace(
                table=state.table.at[sentinel].add(0.25),
                step=state.step + 1,
            )
            save_checkpoint(cfg.model_file, state)
            t_pub = time.time()
            deadline = t_pub + 30.0
            flipped = None
            while time.time() < deadline:
                s1 = float(conn.request({"line": line}, timeout=30)["score"])
                if abs(s1 - s0) > 1e-9:
                    flipped = (time.time() - t_pub) * 1e3
                    break
                time.sleep(0.002)
            if flipped is None:
                unanswered += 1
                log(f"probe-freshness: trial {trial} never flipped (30s)")
            else:
                flips_ms.append(flipped)
                log(f"probe-freshness: trial {trial} flipped in {flipped:.1f} ms")
        stats = conn.request({"op": "stats"}, timeout=60)
    finally:
        conn.close()
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
    engines = stats.get("engines", {})
    steady = [
        e.get("steady_compiles")
        for e in engines.values()
        if isinstance(e.get("steady_compiles"), int)
    ]
    result = {
        "probe": "PROBE_FRESHNESS",
        **artifact_stamp(stats.get("run_id", "")),
        "trials": args.probe_freshness,
        "unanswered": unanswered,
        "replicas": cfg.serve_replicas,
        "reload_interval_s": cfg.serve_reload_interval_s,
        "publish_to_first_scored_ms": percentiles_ms([x / 1e3 for x in flips_ms]),
        "engine_freshness_scored_ms": {
            k: (e.get("engine") or {}).get("freshness_scored_ms")
            for k, e in sorted(engines.items())
        },
        "engine_freshness_applied_ms": {
            k: (e.get("engine") or {}).get("freshness_applied_ms")
            for k, e in sorted(engines.items())
        },
        "fleet_freshness": stats.get("freshness"),
        "steady_state_recompiles": max(steady) if steady else None,
        "note": (
            "black-box SLO: sentinel scored through the 2-connection wire; "
            "flip latency includes router reload poll + restore + swap + "
            "flush.  engine_* histograms are the white-box twin measured "
            "against the checkpoint's embedded publish stamp."
        ),
    }
    out = json.dumps(result, indent=2)
    print(out)
    if args.out:
        write_json_artifact(args.out, result, indent=2, sort_keys=False)
    return 0 if flips_ms and not unanswered else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("config", help="INI config (uses [Serving] + model_file)")
    ap.add_argument("--mode", choices=("open", "closed"), default="open")
    ap.add_argument("--qps", type=float, default=500.0, help="open-loop arrival rate")
    ap.add_argument("--concurrency", type=int, default=8, help="closed-loop workers")
    ap.add_argument("--duration", type=float, default=3.0, help="seconds of traffic")
    ap.add_argument("--requests", type=int, default=10**9, help="request cap")
    ap.add_argument("--input", default=None, help="libsvm file of request lines")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="also write the JSON here")
    ap.add_argument(
        "--connect", default=None, metavar="HOST:PORT",
        help="drive a LIVE socket front end instead of an in-process engine",
    )
    ap.add_argument(
        "--spawn", action="store_true",
        help="spawn `serve <cfg> --port 0` (replicated front end) and drive it",
    )
    ap.add_argument(
        "--connections", type=int, default=4, metavar="C",
        help="socket open-loop: parallel pipelined connections, each at qps/C "
        "(the multi-connection sender that makes 10k+ QPS drivable)",
    )
    ap.add_argument(
        "--wire", choices=("binary", "jsonl"), default="binary",
        help="DATA wire for the socket open loop: binary frames pinned to "
        "a replica (negotiated — falls back to JSONL when the server "
        "refuses), or force the per-line JSONL path",
    )
    ap.add_argument(
        "--frame-rows", type=int, default=32, metavar="R",
        help="rows coalesced per binary REQUEST frame (clamped to the "
        "replica's negotiated max_frame_rows)",
    )
    ap.add_argument(
        "--processes", type=int, default=1, metavar="N",
        help="open-loop socket mode: fan the schedule across N worker "
        "processes at qps/N each (one Python sender tops out ~25k offered; "
        "50k+ needs processes, not threads)",
    )
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument(
        "--classes", default=None, metavar="MIX",
        help="client-class traffic mix, e.g. gold:0.1,std:0.9 (tiers come "
        "from the server's serve_classes)",
    )
    ap.add_argument(
        "--deadline-ms", type=float, default=0.0, metavar="MS",
        help="stamp a per-request deadline (0 = none) — exercises the "
        "deadline-shed path under load",
    )
    ap.add_argument(
        "--init-missing-checkpoint",
        action="store_true",
        help="write a fresh random checkpoint when model_file is absent",
    )
    ap.add_argument(
        "--stats",
        action="store_true",
        help="poll the live tier's `stats` admin op once (router + "
        "per-replica counters + fleet freshness) and print it as ONE JSON "
        "line — the operator path that needs no JSONL tailing.  Requires "
        "--connect or --spawn",
    )
    ap.add_argument(
        "--probe-freshness",
        type=int,
        default=0,
        metavar="TRIALS",
        help="tagged-probe freshness mode: per trial, score a sentinel id, "
        "publish a checkpoint whose sentinel row changed, and poll the "
        "sentinel's score through the front end until it flips — the "
        "black-box publish→first-scored-with-new-rows SLO.  Emits a "
        "PROBE_FRESHNESS JSON (use --out).  Requires --spawn (the probe "
        "must own model_file to publish)",
    )
    args = ap.parse_args(argv)
    if args.stats and not (args.connect or args.spawn):
        ap.error("--stats requires --connect or --spawn (a live front end)")
    if args.probe_freshness:
        if not args.spawn:
            ap.error(
                "--probe-freshness requires --spawn (the probe publishes "
                "checkpoints into model_file, so it must own the tier)"
            )
        if args.probe_freshness < 2:
            ap.error("--probe-freshness needs >= 2 trials for percentiles")
    if args.mode == "open" and args.qps <= 0:
        ap.error("--qps must be > 0 in open mode (it is the Poisson arrival rate)")
    if args.mode == "closed" and args.concurrency < 1:
        ap.error("--concurrency must be >= 1 in closed mode")
    if args.connections < 1:
        ap.error("--connections must be >= 1")
    if args.connect and args.spawn:
        ap.error("--connect and --spawn are mutually exclusive")
    if args.frame_rows < 1:
        ap.error("--frame-rows must be >= 1")
    if args.processes < 1:
        ap.error("--processes must be >= 1")
    if args.processes > 1 and not (args.connect or args.spawn):
        ap.error("--processes requires the socket transport (--connect/--spawn)")
    if args.processes > 1 and args.mode != "open":
        ap.error("--processes is an open-loop fan-out (use --mode open)")
    if args.worker and not args.connect:
        ap.error("--worker requires --connect (the parent owns the tier)")
    mix = parse_class_mix(args.classes) if args.classes else None

    from fast_tffm_tpu.config import build_model, load_config

    cfg = load_config(args.config)
    if args.init_missing_checkpoint and not os.path.exists(cfg.model_file.rstrip("/")):
        import jax

        from fast_tffm_tpu.checkpoint import save_checkpoint
        from fast_tffm_tpu.trainer import init_state

        save_checkpoint(
            cfg.model_file,
            init_state(
                build_model(cfg),
                jax.random.key(args.seed),
                cfg.init_accumulator_value,
                cfg.adagrad_accumulator,
            ),
        )
        print(f"loadgen: wrote fresh checkpoint {cfg.model_file}", file=sys.stderr)

    log = lambda *a: print(*a, file=sys.stderr)

    if args.stats:
        # One-shot operator poll: the `stats` admin op over the CONTROL
        # path of a live tier, printed as ONE JSON line — router counters,
        # per-replica engine snapshots, fleet freshness percentiles.
        proc = None
        if args.spawn:
            proc, port = spawn_serve(args.config, log=log)
            host = "127.0.0.1"
        else:
            host, _, port = args.connect.rpartition(":")
            host, port = host or "127.0.0.1", int(port)
        try:
            c = ServeConnection(port, host=host)
            try:
                stats = c.request({"op": "stats"}, timeout=60)
            finally:
                c.close()
            print(json.dumps(stats, separators=(",", ":")))
        finally:
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
        return 0

    if args.probe_freshness:
        return run_freshness_probe(args, cfg, log)

    if args.input:
        lines = [l.strip() for l in open(args.input) if l.strip()]
    elif cfg.predict_files:
        lines = [
            l.strip() for p in cfg.predict_files for l in open(p) if l.strip()
        ]
    else:
        width = cfg.max_nnz if cfg.max_nnz > 0 else 8
        lines = synth_lines(cfg, 4096, width, args.seed)
        print(f"loadgen: synthesized {len(lines)} request lines", file=sys.stderr)

    if args.worker:
        return run_worker(args, cfg, lines, mix)

    res = Results()
    result: dict = {
        "bench": "BENCH_SERVE",
        "mode": args.mode,
        "qps_target": args.qps if args.mode == "open" else None,
        "concurrency": args.concurrency if args.mode == "closed" else None,
        "class_mix": dict(mix) if mix else None,
        "deadline_ms": args.deadline_ms or None,
        "flush_deadline_ms": cfg.serve_flush_deadline_ms,
    }

    if args.connect or args.spawn:
        proc = None
        if args.spawn:
            t_setup = time.perf_counter()
            proc, port = spawn_serve(args.config, log=log)
            host = "127.0.0.1"
            warmup_s = time.perf_counter() - t_setup
        else:
            host, _, port = args.connect.rpartition(":")
            host, port = host or "127.0.0.1", int(port)
            warmup_s = 0.0
        try:
            if args.mode == "open":
                if args.processes > 1:
                    extra = run_multiprocess(args, host, port, res)
                else:
                    extra = drive_open(host, port, lines, cfg, args, mix, res)
                wall = extra.pop("wall")
                # The no-hung-client pin: anything STILL unresolved after
                # the drain window never got its one response.
                result["unanswered"] = extra.pop("unanswered")
                result.update(extra)
            else:
                t0 = time.perf_counter()
                run_closed_socket(port, host, lines, args, mix, res)
                wall = time.perf_counter() - t0
            c = ServeConnection(port, host=host)
            try:
                stats = c.request({"op": "stats"}, timeout=60)
            finally:
                c.close()
            engines = stats.get("engines", {})
            steady = [
                e.get("steady_compiles")
                for e in engines.values()
                if isinstance(e.get("steady_compiles"), int)
            ]
            from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact

            result.update(
                # Join keys: the tier's run_id + envelope schema version —
                # this artifact is joinable to the replicas' JSONL streams.
                **artifact_stamp(stats.get("run_id", "")),
                freshness=stats.get("freshness"),
            )
            result.update(
                transport="socket",
                connections=args.connections if args.mode == "open" else None,
                warmup_s=round(warmup_s, 3),
                server={
                    k: stats.get(k)
                    for k in (
                        "replicas",
                        "failovers",
                        "failed_unanswerable",
                        "reload_fanouts",
                        "mttr_s",
                    )
                },
                engines=engines,
                steady_state_recompiles=max(steady) if steady else None,
            )
        finally:
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
    else:
        from fast_tffm_tpu.serving import ServingEngine

        if args.mode == "open" and cfg.serve_overload == "block":
            # A blocking submit would stall the Poisson arrival schedule
            # the moment the queue fills — turning the open loop into a
            # closed one exactly at the queueing-collapse point it exists
            # to expose.  Shed instead; rejects are counted in the result.
            import dataclasses

            cfg = dataclasses.replace(cfg, serve_overload="reject")
            print(
                "loadgen: open-loop mode forces serve_overload = reject "
                "(blocking submits would self-throttle the arrival schedule)",
                file=sys.stderr,
            )
        t_setup = time.perf_counter()
        engine = ServingEngine(cfg, log=log)
        warm = engine.compile_count()  # ladder fully compiled here (ctor warmup)
        warmup_s = time.perf_counter() - t_setup
        t0 = time.perf_counter()
        if args.mode == "open":
            run_open_engine(engine, lines, args, mix, res)
        else:
            run_closed_engine(engine, lines, args, mix, res)
        wall = time.perf_counter() - t0
        end = engine.compile_count()
        snap = engine.metrics_snapshot()
        run_id = engine.run_id
        engine.close()
        from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact

        result.update(
            **artifact_stamp(run_id),
            transport="inprocess",
            warmup_s=round(warmup_s, 3),
            buckets=list(engine.buckets),
            overload=cfg.serve_overload,
            # Flat compile count across the traffic phase IS the
            # acceptance signal: every request shape landed on a warmed
            # bucket.
            compile_count_warm=warm,
            compile_count_end=end,
            steady_state_recompiles=(
                end - warm if warm is not None and end is not None else None
            ),
            **snap,
        )

    result.update(
        duration_s=round(wall, 3),
        requests_sent=res.sent,
        requests_scored=len(res.lat),
        qps_achieved=round(len(res.lat) / wall, 1) if wall > 0 else None,
        client_ms=percentiles_ms(res.lat),
        client_ms_by_class={
            k: percentiles_ms(v) for k, v in sorted(res.lat_by_class.items())
        },
        shed_codes=dict(sorted(res.codes.items())),
    )
    out = json.dumps(result, indent=2)
    print(out)
    if args.out:
        write_json_artifact(args.out, result, indent=2, sort_keys=False)
    return 0


if __name__ == "__main__":
    sys.exit(main())
