#!/usr/bin/env python
"""Serving load generator: drive a ServingEngine, emit BENCH_SERVE JSON.

The serving analog of bench.py's train BENCH files: one JSON object with
client-observed latency percentiles (p50/p95/p99), achieved QPS, the
engine's own queue/compute/occupancy metrics, and the compile counts
that pin "zero steady-state recompiles" — so future PRs can track a
serving trajectory the way BENCH_r*.json tracks training.

Two modes:

  * ``open`` (default) — open-loop Poisson arrivals at ``--qps``: the
    generator submits on a fixed random schedule whether or not earlier
    requests finished, which is what exposes queueing collapse (a
    closed loop self-throttles and hides it).
  * ``closed`` — ``--concurrency`` workers each submit-and-wait in a
    loop: measures best-case service latency and saturation throughput.

Request sizes are MIXED by construction (per-line nnz drawn 1..max_nnz)
so the run exercises every ladder bucket.

Usage:
    python tools/loadgen.py run.cfg --mode open --qps 500 --duration 3
    python tools/loadgen.py run.cfg --mode closed --concurrency 8 \
        --requests 2000 --out BENCH_SERVE.json

With no --input and no predict_files, synthetic libsvm lines are drawn
from the configured vocabulary; --init-missing-checkpoint writes a fresh
random checkpoint when model_file is absent (zero-setup smoke runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synth_lines(cfg, n: int, max_nnz: int, seed: int) -> list[str]:
    """Random libsvm lines over the configured vocab, nnz mixed 1..max_nnz
    so the bucket ladder (and its padding) sees every width."""
    rng = np.random.default_rng(seed)
    v = min(cfg.vocabulary_size, 1 << 20)
    lines = []
    for _ in range(n):
        # Clamp to the vocab: choice(replace=False) can't draw k > v.
        k = int(rng.integers(1, min(max_nnz, v) + 1))
        ids = rng.choice(v, size=k, replace=False)
        vals = np.round(np.abs(rng.normal(size=k)) + 0.1, 4)
        toks = " ".join(f"{i}:{x}" for i, x in zip(ids, vals))
        lines.append(f"{int(rng.integers(0, 2))} {toks}")
    return lines


def run_open(engine, lines, qps: float, duration: float, max_requests: int, seed: int):
    """Open-loop Poisson arrivals; returns client latencies (seconds)."""
    rng = np.random.default_rng(seed)
    lat: list[float] = []
    lat_lock = threading.Lock()
    inflight: list = []
    t_end = time.perf_counter() + duration
    i = sent = 0
    t_next = time.perf_counter()
    while time.perf_counter() < t_end and sent < max_requests:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(min(t_next - now, 0.005))
            continue
        t_next += rng.exponential(1.0 / qps)
        t0 = time.perf_counter()
        try:
            fut = engine.submit_line(lines[i % len(lines)])
        except Exception:
            i += 1
            continue  # rejected (overload policy): engine counts it
        def _record(f, t0=t0):
            if f.exception() is None:
                with lat_lock:
                    lat.append(time.perf_counter() - t0)

        fut.add_done_callback(_record)
        inflight.append(fut)
        i += 1
        sent += 1
    for f in inflight:
        try:
            f.result(timeout=30)
        except Exception:
            pass
    return lat, sent


def run_closed(engine, lines, concurrency: int, duration: float, max_requests: int):
    """Closed-loop submit-and-wait workers; returns client latencies."""
    lat: list[float] = []
    lock = threading.Lock()
    stop = time.perf_counter() + duration
    counter = [0]

    def worker(wid: int):
        i = wid
        while time.perf_counter() < stop:
            with lock:
                if counter[0] >= max_requests:
                    return
                counter[0] += 1
            t0 = time.perf_counter()
            try:
                s = engine.submit_line(lines[i % len(lines)]).result(timeout=30)
                del s
            except Exception:
                # Advance past the failing line (a reject, or one bad
                # input row) and yield briefly — retrying the SAME line
                # in a tight loop would busy-spin the whole --duration.
                i += concurrency
                time.sleep(0.001)
                continue
            with lock:
                lat.append(time.perf_counter() - t0)
            i += concurrency

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lat, counter[0]


def percentiles_ms(lat: list[float]) -> dict:
    if not lat:
        return {"count": 0}
    a = np.asarray(lat) * 1e3
    return {
        "count": int(a.size),
        "mean": round(float(a.mean()), 3),
        "p50": round(float(np.percentile(a, 50)), 3),
        "p95": round(float(np.percentile(a, 95)), 3),
        "p99": round(float(np.percentile(a, 99)), 3),
        "max": round(float(a.max()), 3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("config", help="INI config (uses [Serving] + model_file)")
    ap.add_argument("--mode", choices=("open", "closed"), default="open")
    ap.add_argument("--qps", type=float, default=500.0, help="open-loop arrival rate")
    ap.add_argument("--concurrency", type=int, default=8, help="closed-loop workers")
    ap.add_argument("--duration", type=float, default=3.0, help="seconds of traffic")
    ap.add_argument("--requests", type=int, default=10**9, help="request cap")
    ap.add_argument("--input", default=None, help="libsvm file of request lines")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="also write the JSON here")
    ap.add_argument(
        "--init-missing-checkpoint",
        action="store_true",
        help="write a fresh random checkpoint when model_file is absent",
    )
    args = ap.parse_args(argv)
    if args.mode == "open" and args.qps <= 0:
        ap.error("--qps must be > 0 in open mode (it is the Poisson arrival rate)")
    if args.mode == "closed" and args.concurrency < 1:
        ap.error("--concurrency must be >= 1 in closed mode")

    from fast_tffm_tpu.config import build_model, load_config
    from fast_tffm_tpu.serving import ServingEngine

    cfg = load_config(args.config)
    if args.mode == "open" and cfg.serve_overload == "block":
        # A blocking submit would stall the Poisson arrival schedule the
        # moment the queue fills — turning the open loop into a closed
        # one exactly at the queueing-collapse point it exists to expose.
        # Shed instead; rejects are counted in the result.
        import dataclasses

        cfg = dataclasses.replace(cfg, serve_overload="reject")
        print(
            "loadgen: open-loop mode forces serve_overload = reject "
            "(blocking submits would self-throttle the arrival schedule)",
            file=sys.stderr,
        )
    if args.init_missing_checkpoint and not os.path.exists(cfg.model_file.rstrip("/")):
        import jax

        from fast_tffm_tpu.checkpoint import save_checkpoint
        from fast_tffm_tpu.trainer import init_state

        save_checkpoint(
            cfg.model_file,
            init_state(
                build_model(cfg),
                jax.random.key(args.seed),
                cfg.init_accumulator_value,
                cfg.adagrad_accumulator,
            ),
        )
        print(f"loadgen: wrote fresh checkpoint {cfg.model_file}", file=sys.stderr)

    if args.input:
        lines = [l.strip() for l in open(args.input) if l.strip()]
    elif cfg.predict_files:
        lines = [
            l.strip() for p in cfg.predict_files for l in open(p) if l.strip()
        ]
    else:
        width = cfg.max_nnz if cfg.max_nnz > 0 else 8
        lines = synth_lines(cfg, 4096, width, args.seed)
        print(f"loadgen: synthesized {len(lines)} request lines", file=sys.stderr)

    log = lambda *a: print(*a, file=sys.stderr)
    t_setup = time.perf_counter()
    engine = ServingEngine(cfg, log=log)
    warm = engine.compile_count()  # ladder fully compiled here (ctor warmup)
    t_warm = time.perf_counter() - t_setup

    t0 = time.perf_counter()
    if args.mode == "open":
        lat, sent = run_open(
            engine, lines, args.qps, args.duration, args.requests, args.seed
        )
    else:
        lat, sent = run_closed(
            engine, lines, args.concurrency, args.duration, args.requests
        )
    wall = time.perf_counter() - t0
    end = engine.compile_count()
    snap = engine.metrics_snapshot()
    engine.close()

    result = {
        "bench": "BENCH_SERVE",
        "mode": args.mode,
        "qps_target": args.qps if args.mode == "open" else None,
        "concurrency": args.concurrency if args.mode == "closed" else None,
        "duration_s": round(wall, 3),
        "warmup_s": round(t_warm, 3),
        "requests_sent": sent,
        "requests_scored": len(lat),
        "qps_achieved": round(len(lat) / wall, 1) if wall > 0 else None,
        "client_ms": percentiles_ms(lat),
        "buckets": list(engine.buckets),
        "flush_deadline_ms": cfg.serve_flush_deadline_ms,
        "overload": cfg.serve_overload,
        # Flat compile count across the traffic phase IS the acceptance
        # signal: every request shape landed on a warmed bucket.
        "compile_count_warm": warm,
        "compile_count_end": end,
        "steady_state_recompiles": (
            end - warm if warm is not None and end is not None else None
        ),
        **snap,
    }
    out = json.dumps(result, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
