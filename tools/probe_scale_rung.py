#!/usr/bin/env python
"""Bisect the 235M-row rung regression (VERDICT r3 weak #1 / next #5).

BENCH_r02 measured a 234,881,024-row table; BENCH_r03's fresh-subprocess
probe got RESOURCE_EXHAUSTED at the same size.  This tool isolates WHICH
stage fails, each stage in its OWN fresh subprocess (a failed big
allocation poisons the process — bench._probe_rung):

  alloc      build the [V, 9] table + [V, 1] row accumulator, value-sync
  alloc_el   same with the ELEMENT [V, 9] accumulator (2.2 GB more)
  step       alloc + compile + run one donated train step (the r02 regime)
  step_nodon step without donation (XLA must double-buffer the table)
  step_b4096 donated step at BATCH=4096 (VERDICT r4 #6: smaller per-step
             transients — isolates batch-sized temporaries from the table)
  step_packed lane-packed table + row accumulator + the sort-free COMPACT
             update (r5): [VP, 128] layout, O(M) transients — the scale
             regime's intended production path

Run with no args for the driver sweep over sizes around the regression;
`python tools/probe_scale_rung.py <stage> <vocab>` runs one stage.  The
sweep records the XLA_FLAGS in effect so flag-variation retries are
distinguishable artifacts (VERDICT r4 #6).  Prints one JSON dict.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = ("alloc", "alloc_el", "step", "step_nodon", "step_b4096", "step_packed")
SIZES = (1 << 27, 201_326_592, 234_881_024, 251_658_240, 1 << 28)


def run_stage(stage: str, vocab: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    from bench import BATCH, NNZ, SCALE_K, forced_sync, make_batch, scale_state, zipf_ids
    from fast_tffm_tpu.models import FMModel
    from fast_tffm_tpu.optim import AdagradState
    from fast_tffm_tpu.trainer import TrainState, train_step_body

    t0 = time.perf_counter()
    if stage in ("alloc", "alloc_el"):
        state = scale_state(vocab, SCALE_K)
        if stage == "alloc_el":
            state = TrainState(
                state.table,
                AdagradState(jnp.full((vocab, 1 + SCALE_K), 0.1, jnp.float32)),
                {}, AdagradState({}), state.step,
            )
        forced_sync(state)
    elif stage == "step_packed":
        from fast_tffm_tpu.ops.packed_table import LANES, packed_rows, rows_per_tile
        from fast_tffm_tpu.trainer import make_packed_train_step

        rng = np.random.default_rng(0)
        model = FMModel(vocabulary_size=vocab, factor_num=SCALE_K, order=2)
        d = 1 + SCALE_K
        vp = packed_rows(vocab, d)
        table = jax.jit(
            lambda k: jax.random.uniform(k, (vp, LANES), jnp.float32, -0.01, 0.01)
        )(jax.random.key(0))
        state = TrainState(
            table, AdagradState(jnp.full((vp, rows_per_tile(d)), 0.1, jnp.float32)),
            {}, AdagradState({}), jnp.zeros((), jnp.int32),
        )
        step = make_packed_train_step(model, 0.01, "compact")
        b = make_batch(zipf_ids(rng, (BATCH, NNZ), vocab), 0)
        state, _ = step(state, b)
        forced_sync(state)
    else:
        rng = np.random.default_rng(0)
        model = FMModel(vocabulary_size=vocab, factor_num=SCALE_K, order=2)
        donate = () if stage == "step_nodon" else (0,)
        batch_size = 4096 if stage == "step_b4096" else BATCH
        step = jax.jit(
            partial(train_step_body, model, 0.01), donate_argnums=donate
        )
        b = make_batch(zipf_ids(rng, (batch_size, NNZ), vocab), 0)
        state = scale_state(vocab, SCALE_K)
        state, _ = step(state, b)
        forced_sync(state)
    print(f"OK {stage} vocab={vocab} {time.perf_counter() - t0:.1f}s", flush=True)
    raise SystemExit(0)


def main() -> None:
    res = {"xla_flags": os.environ.get("XLA_FLAGS", "")}
    for vocab in SIZES:
        for stage in STAGES:
            key = f"{stage}@{vocab}"
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), stage, str(vocab)],
                    capture_output=True, text=True, timeout=600,
                )
            except subprocess.TimeoutExpired:
                res[key] = "timeout600"
                print(json.dumps({key: res[key]}), flush=True)
                continue
            if r.returncode == 0:
                line = [l for l in r.stdout.splitlines() if l.startswith("OK")]
                res[key] = line[-1] if line else "ok"
            else:
                lines = [
                    l.strip() for l in (r.stderr or r.stdout).splitlines() if l.strip()
                ]
                err = next(
                    (l for l in reversed(lines) if "Error" in l or "error" in l),
                    lines[-1] if lines else "?",
                )
                res[key] = f"FAIL {err[:140]}"
            print(json.dumps({key: res[key]}), flush=True)
        # Stop probing bigger sizes once even the bare alloc fails — the
        # later stages are strictly harder.
        if str(res.get(f"alloc@{vocab}", "")).startswith(("FAIL", "timeout")):
            break
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] in STAGES:
        run_stage(sys.argv[1], int(sys.argv[2]))
    main()
