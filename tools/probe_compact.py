#!/usr/bin/env python
"""A/B probe: packed sparse-tail strategies at the giant-vocab scale rung.

Measures, each config in its OWN subprocess (a failed rung leaks device
buffers for the life of the process on this backend — bench.py:_probe_rung):

  rows      the r4 scale-rung step (rows layout, row accumulator) — baseline
  compact   lane-packed table + sort-free touched-row compaction
            (ops/packed_table.py:packed_compact_adagrad_update), row accum
  compact-element / sorted-element
            element-accumulator variants (packed element accum is a second
            table-sized array — expected to OOM at the 201M rung; recorded)

Writes PROBE_COMPACT_r05.json at the repo root.  Usage:
  python tools/probe_compact.py                 # full ladder
  python tools/probe_compact.py --one CFG VOCAB BATCH   # one config, one line
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB = 201_326_592
BATCH = 16384
K = 8


def _one(cfg: str, vocab: int, batch: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from fast_tffm_tpu.models import FMModel
    from fast_tffm_tpu.optim import AdagradState
    from fast_tffm_tpu.trainer import TrainState, make_train_step, make_packed_train_step
    from fast_tffm_tpu.ops.packed_table import LANES, packed_rows, rows_per_tile

    rng = np.random.default_rng(0)
    model = FMModel(vocabulary_size=vocab, factor_num=K, order=2)
    batches = [
        bench.make_batch(bench.zipf_ids(rng, (batch, bench.NNZ), vocab), i)
        for i in range(4)
    ]

    if cfg == "rows":
        step = make_train_step(model, learning_rate=0.01)
        state = bench.scale_state(vocab, K)
    elif cfg in ("fused", "fused-dense", "fused-capped"):
        # The ONE fused-state builder lives in bench.py — duplicating the
        # stride-(d+1) lane init here would let the probe drift from what
        # the bench actually measures.
        state = bench.fused_scale_state(vocab, K)
        step = make_packed_train_step(
            model, learning_rate=0.01,
            update="dense" if cfg == "fused-dense" else "compact",
            # Zipf(1.1) at B=65536 measures ~0.5M unique physical rows;
            # cap at 2^20 with the exact lax.cond fallback.
            compact_cap=(1 << 20) if cfg == "fused-capped" else 0,
        )
    else:
        update, accum = {
            "compact": ("compact", "row"),
            "compact-element": ("compact", "element"),
            "sorted-element": ("sorted", "element"),
            "dense": ("dense", "row"),
        }[cfg]
        d = 1 + K
        p = rows_per_tile(d)
        vp = packed_rows(vocab, d)

        from functools import partial

        @partial(jax.jit, static_argnums=(1, 2))
        def mk(key, n, c):
            return jax.random.uniform(key, (n, c), jnp.float32, -0.01, 0.01)

        acc_cols = p if accum == "row" else LANES
        state = TrainState(
            table=mk(jax.random.key(0), vp, LANES),
            table_opt=AdagradState(jnp.full((vp, acc_cols), 0.1, jnp.float32)),
            dense={},
            dense_opt=AdagradState({}),
            step=jnp.zeros((), jnp.int32),
        )
        step = make_packed_train_step(model, learning_rate=0.01, update=update)

    state, rate = bench.measure(step, state, batches, iters=20, batch_size=batch)
    print(json.dumps({"cfg": cfg, "vocab": vocab, "batch": batch,
                      "rate_per_chip": round(rate / jax.device_count(), 1)}))


def main() -> None:
    results = {"vocab": VOCAB, "batch": BATCH, "configs": {}}
    for cfg in ("compact", "rows", "compact-element", "sorted-element"):
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", cfg,
                 str(VOCAB), str(BATCH)],
                capture_output=True, text=True, timeout=1500,
            )
        except subprocess.TimeoutExpired:
            results["configs"][cfg] = {"error": "timeout (1500s)"}
            continue
        line = (r.stdout or "").strip().splitlines()
        if r.returncode == 0 and line:
            results["configs"][cfg] = json.loads(line[-1])
        else:
            err = [l for l in (r.stderr or "").strip().splitlines() if l][-3:]
            results["configs"][cfg] = {"error": " | ".join(err)[-400:]}
        print(cfg, "->", results["configs"][cfg], flush=True)
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "PROBE_COMPACT_r05.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        _one(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    else:
        main()
