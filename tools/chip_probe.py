#!/usr/bin/env python
"""One-line chip health probe: can we allocate + step at vocab 2^20?"""
import sys, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from fast_tffm_tpu.telemetry import arm_hang_exit
_w = arm_hang_exit(seconds=420, what="chip_probe")
import jax, numpy as np
import bench as B
from fast_tffm_tpu.models import FMModel
from fast_tffm_tpu.trainer import init_state, make_train_step
try:
    vocab = 1 << 20
    model = FMModel(vocabulary_size=vocab, factor_num=8, order=2)
    step = make_train_step(model, 0.01)
    rng = np.random.default_rng(0)
    bats = [B.make_batch(B.zipf_ids(rng, (B.BATCH, B.NNZ), vocab), i) for i in range(4)]
    state = init_state(model, jax.random.key(0))
    t0 = time.perf_counter()
    state, rate = B.measure(step, state, bats, iters=5, windows=1)
    print(f"HEALTHY rate={rate:,.0f} ex/s step={B.BATCH/rate*1e3:.0f}ms wall={time.perf_counter()-t0:.0f}s")
except Exception as e:
    print(f"DEGRADED {str(e)[:90]}")
_w.cancel()
