#!/usr/bin/env python
"""Synthetic CTR dataset generator (libsvm / libffm text).

The reference project shipped a small sample data file as its de-facto test
input (`renyi533/fast_tffm` :: repo-root sample data + sample.cfg; SURVEY.md
§5 "the de-facto test is running train/predict on a bundled sample data
file").  No dataset ships in this environment, so this tool generates
statistically Criteo/Avazu/KDD-shaped data with a PLANTED factorization
-machine signal, so that training on it produces a genuinely learnable AUC
(the e2e smoke's success criterion) rather than coin-flip labels:

  * one feature id per field, drawn Zipf-like within the field's id range
    (CTR data is heavy-tailed: a few ids dominate);
  * labels ~ Bernoulli(sigmoid(score)) where score comes from a hidden FM
    (bias + order-2 interactions) over the drawn ids;
  * --format libffm writes `field:feat:val` tokens (FFM), libsvm `feat:val`.

Usage (the configs/ headers reference these exact commands):

  python tools/gen_synthetic.py --rows 100000 --fields 39 --vocab 1048576 \
      --out data/criteo_sample.train.libsvm
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def _zipf_ids(rng, n_rows: int, field_lo: int, field_hi: int) -> np.ndarray:
    """Heavy-tailed id draw within [field_lo, field_hi)."""
    span = field_hi - field_lo
    # Inverse-CDF of a truncated power law: rank ~ u^alpha spreads mass onto
    # low ranks; permuting ranks decorrelates popularity from id order.
    u = rng.random(n_rows)
    ranks = np.minimum((span * u**2.5).astype(np.int64), span - 1)
    return field_lo + ranks


def _mix64(x: np.ndarray, salt: int) -> np.ndarray:
    """SplitMix64 finalizer: uint64 → well-mixed uint64 (vectorized)."""
    z = (x.astype(np.uint64) + np.uint64(salt * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _id_normal(ids: np.ndarray, salt: int) -> np.ndarray:
    """Deterministic N(0,1) draw per feature id (Box-Muller over two hashes).

    The hidden FM's parameters MUST be a pure function of the id value, not
    of any per-file state — train/validation/test files generated in
    separate calls have to score examples with the SAME planted model, or
    held-out AUC is structurally pinned at 0.5.
    """
    with np.errstate(divide="ignore"):
        u1 = (_mix64(ids, 2 * salt) >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        u2 = (_mix64(ids, 2 * salt + 1) >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        z = np.sqrt(-2.0 * np.log(np.maximum(u1, 1e-300))) * np.cos(2.0 * np.pi * u2)
    return z.astype(np.float32)


def planted_score(ids: np.ndarray, vals: np.ndarray, factor_num: int = 4,
                  model_seed: int = 1234) -> np.ndarray:
    """Score rows with the planted FM (bias + order-2 interactions).

    The single source of the planted model's math and constants — generate()
    labels with it and benchmark oracles replay it on parsed files.  AUC
    consumers can use these raw scores directly: generate()'s affine
    calibration is rank-preserving.  ids/vals: [rows, nnz]."""
    bias = 0.6 * _id_normal(ids, model_seed)
    fac = np.stack(
        [0.45 * _id_normal(ids, model_seed + 7 + j) for j in range(factor_num)],
        axis=-1,
    )
    vx = fac * np.asarray(vals, np.float32)[..., None]
    s1 = vx.sum(axis=1)
    inter = 0.5 * ((s1 * s1).sum(-1) - (vx * vx).sum(axis=(1, 2)))
    return (bias * vals).sum(axis=1) + inter


def generate(
    out: str,
    rows: int,
    fields: int,
    vocab: int,
    fmt: str = "libsvm",
    factor_num: int = 4,
    seed: int = 0,
    binary_vals: bool = False,
    model_seed: int = 1234,
    spread: float = 1.5,
) -> None:
    rng = np.random.default_rng(seed)
    # Field f owns the id range [f*vocab//fields, (f+1)*vocab//fields).
    bounds = np.linspace(0, vocab, fields + 1).astype(np.int64)

    ids = np.stack(
        [_zipf_ids(rng, rows, bounds[f], bounds[f + 1]) for f in range(fields)],
        axis=1,
    )  # [rows, fields]
    vals = (
        np.ones((rows, fields), np.float32)
        if binary_vals
        else np.round(np.abs(rng.normal(0.5, 0.35, size=(rows, fields))) + 0.05, 4).astype(
            np.float32
        )
    )

    # Hidden FM: per-id bias + factors as a stateless function of (id,
    # model_seed) — files generated with different --seed but the same
    # --model-seed share one planted model, so held-out AUC is meaningful.
    score = planted_score(ids, vals, factor_num, model_seed)
    # Calibrated spread: bigger -> labels closer to deterministic (higher
    # oracle AUC, cleaner learning signal); 1.5 looks like real CTR noise.
    score = (score - score.mean()) / (score.std() + 1e-6) * spread
    labels = (rng.random(rows) < 1.0 / (1.0 + np.exp(-score))).astype(np.int64)

    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    # Values print as fixed 4-decimal tokens: vals were rounded to 4 decimals,
    # so this round-trips to the same float32 — and it matches how real CTR
    # dumps look (a float32's 17-digit shortest repr does not, and pushes
    # every token off the parser's exact fast path into strtod).
    with open(out, "w") as f:
        for r in range(rows):
            if fmt == "libffm":
                toks = " ".join(
                    f"{fi}:{ids[r, fi]}:{vals[r, fi]:.4f}" for fi in range(fields)
                )
            else:
                toks = " ".join(
                    f"{ids[r, fi]}:{vals[r, fi]:.4f}" for fi in range(fields)
                )
            f.write(f"{labels[r]} {toks}\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="output text file")
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--fields", type=int, default=39, help="features per example")
    ap.add_argument("--vocab", type=int, default=1 << 20)
    ap.add_argument("--format", choices=("libsvm", "libffm"), default="libsvm")
    ap.add_argument("--factor-num", type=int, default=4, help="hidden FM rank")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--binary-vals", action="store_true", help="all feature values 1.0 (one-hot style)"
    )
    ap.add_argument(
        "--model-seed",
        type=int,
        default=1234,
        help="seed of the PLANTED model (keep equal across train/valid/test splits)",
    )
    ap.add_argument(
        "--spread",
        type=float,
        default=1.5,
        help="planted score std; bigger = less label noise, higher oracle AUC",
    )
    a = ap.parse_args(argv)
    generate(
        a.out,
        a.rows,
        a.fields,
        a.vocab,
        a.format,
        a.factor_num,
        a.seed,
        a.binary_vals,
        a.model_seed,
        a.spread,
    )
    print(f"wrote {a.rows} rows ({a.fields} fields, vocab {a.vocab}, {a.format}) -> {a.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
