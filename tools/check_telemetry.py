#!/usr/bin/env python
"""Static telemetry-envelope conformance check (ISSUE 9 satellite).

The envelope only means something if EVERY record flows through
``telemetry.RunMonitor`` and a kind registered in ``telemetry.SCHEMAS``.
RunMonitor.emit raises on unknown kinds at runtime — but only on code
paths a test actually drives; a new module quietly constructing its own
``MetricsLogger`` (or calling ``.log(kind=...)`` raw) forks the schema
without tripping anything.  This script makes that drift a LOUD tier-1
failure instead (tests/test_telemetry.py runs it):

  1. ``MetricsLogger(`` may only be constructed inside the telemetry
     layer itself (telemetry.py owns it; utils/tracing.py defines it).
  2. Raw ``.log(kind=...)`` emits may only appear in the documented
     duck-type fallback (serving/metrics.py's log_to, for bare
     MetricsLogger sinks) and in tracing.py itself.
  3. Every string-literal kind passed to ``.emit("<kind>", ...)`` in the
     package must be registered in SCHEMAS — an emit of an unregistered
     kind would raise at runtime, but only on the code path a test
     happens to drive; here it fails statically.

Exit 0 = conformant; exit 1 prints every violation with file:line.
Stdlib + the (jax-free) telemetry module only.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "fast_tffm_tpu")

# Files allowed to CONSTRUCT a MetricsLogger (the envelope layer itself).
ALLOW_LOGGER_CONSTRUCTION = {
    "telemetry.py",  # RunMonitor owns the logger
    "utils/tracing.py",  # defines MetricsLogger
}

# Files allowed a raw ``.log(kind=...)`` call.
ALLOW_RAW_KIND_LOG = {
    "utils/tracing.py",  # the logger's own implementation/tests surface
    "serving/metrics.py",  # documented duck-type fallback: log_to() accepts
    #   a bare MetricsLogger for envelope-less callers (tools/tests); every
    #   in-tree engine passes a RunMonitor, which takes the emit() path
}

_RE_LOGGER = re.compile(r"\bMetricsLogger[ \t]*\(")  # same-line call only —
#   a prose mention followed by a parenthetical on the next line is not a
#   construction
_RE_RAW_KIND = re.compile(r"\.log\s*\(\s*kind\s*=")
_RE_EMIT_KIND = re.compile(r"\.emit\s*\(\s*\n?\s*[\"']([a-z_]+)[\"']")


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def check(pkg_dir: str = PKG) -> list[str]:
    sys.path.insert(0, REPO)
    from fast_tffm_tpu.telemetry import SCHEMAS  # jax-free import

    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, pkg_dir).replace(os.sep, "/")
            with open(path) as f:
                text = f.read()
            for m in _RE_LOGGER.finditer(text):
                # Imports/annotations are fine; construction is the fork.
                if rel not in ALLOW_LOGGER_CONSTRUCTION:
                    problems.append(
                        f"{rel}:{_line_of(text, m.start())}: MetricsLogger "
                        "constructed outside the telemetry layer — emit "
                        "through a RunMonitor (telemetry.py) so the record "
                        "carries the envelope"
                    )
            for m in _RE_RAW_KIND.finditer(text):
                if rel not in ALLOW_RAW_KIND_LOG:
                    problems.append(
                        f"{rel}:{_line_of(text, m.start())}: raw .log(kind=...) "
                        "bypasses RunMonitor.emit — the record gets no "
                        "envelope and no schema check"
                    )
            for m in _RE_EMIT_KIND.finditer(text):
                kind = m.group(1)
                if kind not in SCHEMAS:
                    problems.append(
                        f"{rel}:{_line_of(text, m.start())}: emit of "
                        f"unregistered kind {kind!r} — register it (and its "
                        "required keys) in telemetry.SCHEMAS"
                    )
    return problems


def main(argv=None) -> int:
    problems = check()
    if problems:
        print(f"check_telemetry: {len(problems)} violation(s):")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_telemetry: OK — every emitter rides the RunMonitor envelope")
    return 0


if __name__ == "__main__":
    sys.exit(main())
