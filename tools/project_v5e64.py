#!/usr/bin/env python
"""v5e-64 / 10B-parameter projection inputs (VERDICT r4 #2).

Compiles the REAL mesh-sharded train step for a 64-device mesh (virtual
CPU devices — compilation allocates no data buffers) at the north-star
shape (10B params ≈ 1.11B rows at D=9, row accumulator) and at BASELINE
config #2's shape (FM k=16), extracts every cross-device collective from
the compiled HLO, and models per-device wire bytes with standard ring
costs (tests/test_parallel.py:hlo_ici_bytes — the same parser the ICI
test pins).  docs/SCALE.md combines these statics with the measured
single-chip step times into the per-step time budget.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=64 \
      JAX_PLATFORMS=cpu python tools/project_v5e64.py
Writes PROJECT_V5E64_r05.json.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=64"
    ).strip()

import jax

# The harness may pin another platform via env/sitecustomize; jax.config
# wins when applied before backend initialization (tests do the same).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 64)
import jax.numpy as jnp
import numpy as np

from fast_tffm_tpu.models import Batch, FMModel
from fast_tffm_tpu.optim import AdagradState
from fast_tffm_tpu.parallel import make_mesh, make_sharded_train_step
from fast_tffm_tpu.parallel.mesh import ROW_AXIS, batch_sharding, replicated, table_sharding
from fast_tffm_tpu.trainer import TrainState
from tests.test_parallel import hlo_ici_bytes


def wire_bytes(model, mesh, global_batch, nnz, lookup, accum_cols=1,
               capacity_factor=2.0):
    """Per-device ICI wire bytes/step for one (config, mesh, lookup),
    from the compiled HLO — abstract lowering, no arrays materialize."""
    from fast_tffm_tpu.parallel.train_step import _pad_model_vocab

    padded = _pad_model_vocab(model, mesh)
    v, d = padded.vocabulary_size, padded.row_dim
    ts, bs, rep = table_sharding(mesh), batch_sharding(mesh), replicated(mesh)

    def sds(shape, dtype, sh):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    state = TrainState(
        table=sds((v, d), jnp.float32, ts),
        table_opt=AdagradState(sds((v, accum_cols), jnp.float32, ts)),
        dense={},
        dense_opt=AdagradState({}),
        step=sds((), jnp.int32, rep),
    )
    batch = Batch(
        labels=sds((global_batch,), jnp.float32, bs),
        ids=sds((global_batch, nnz), jnp.int32, bs),
        vals=sds((global_batch, nnz), jnp.float32, bs),
        fields=sds((global_batch, 0), jnp.int32, bs),
        weights=sds((global_batch,), jnp.float32, bs),
    )
    step = make_sharded_train_step(
        model, 0.01, mesh, lookup=lookup, capacity_factor=capacity_factor
    )
    hlo = jax.jit(step).lower(state, batch).compile().as_text()
    per_op = hlo_ici_bytes(hlo)
    return {k: round(v) for k, v in per_op.items()}, round(sum(per_op.values()))


def main():
    assert jax.device_count() >= 64, jax.devices()
    out = {"devices": 64, "note": "per-device ICI wire bytes/step from compiled "
           "HLO (ring-cost model, tests/test_parallel.py:hlo_ici_bytes)"}

    # North star: 10B params at D=9 (k=8) -> 1,111,111,168 rows (padded).
    # Per-chip batch 65536 (the measured knee) -> global 4.19M rows/step.
    north = FMModel(vocabulary_size=1_111_111_168, factor_num=8, order=2)
    # BASELINE config #2: FM order-2 k=16 (D=17), same 10B-param budget
    # -> 588,235,294 rows.
    cfg2 = FMModel(vocabulary_size=588_235_294, factor_num=16, order=2)

    per_chip_b = 65536
    cases = []
    for name, model, nnz in (("northstar_k8", north, 39), ("cfg2_k16", cfg2, 39)):
        for data, row in ((1, 64), (4, 16), (8, 8)):
            mesh = make_mesh(data, row, devices=jax.devices()[:64])
            gb = per_chip_b * 64
            for lookup in ("allgather", "alltoall"):
                try:
                    parts, total = wire_bytes(model, mesh, gb, nnz, lookup)
                    cases.append({
                        "config": name, "mesh": f"data{data}xrow{row}",
                        "lookup": lookup, "global_batch": gb,
                        "per_device_wire_bytes": total, "by_op": parts,
                    })
                except Exception as e:
                    cases.append({
                        "config": name, "mesh": f"data{data}xrow{row}",
                        "lookup": lookup, "error": str(e)[:200],
                    })
                print(cases[-1], flush=True)
    out["cases"] = cases
    path = os.path.join(REPO, "PROJECT_V5E64_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
