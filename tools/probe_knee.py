#!/usr/bin/env python
"""Round-4 measurements: dense-G packed step across batch size (the
knee — VERDICT r3 #4), accumulator granularity, and vocab scale.

Value-synced interleaved windows throughout (bench.forced_sync).
Prints one JSON dict; partial results flush on exit.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=3000, what="probe_knee.py")

import jax
import numpy as np

from bench import forced_sync, make_batch, zipf_ids
from fast_tffm_tpu.models import FMModel
from fast_tffm_tpu.trainer import init_packed_state, make_packed_train_step

NNZ = 39
K = 8


def measure_rate(step, state, batches, iters, batch_size, windows=3):
    state, _ = step(state, batches[0])
    forced_sync(state)
    for i in range(1, 3):
        state, _ = step(state, batches[i % len(batches)])
    forced_sync(state)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(iters):
            state, _ = step(state, batches[i % len(batches)])
        forced_sync(state)
        best = min(best, time.perf_counter() - t0)
    return state, batch_size * iters / best


def main():
    rng = np.random.default_rng(0)
    res = {}
    import atexit

    atexit.register(lambda: print(json.dumps(res), flush=True))

    # --- batch knee at vocab 2^24, element accumulator, dense update ---
    vocab = 1 << 24
    model = FMModel(vocabulary_size=vocab, factor_num=K, order=2)
    knee = {}
    for b in (16384, 65536, 262144):
        try:
            step = make_packed_train_step(model, 0.01, "dense")
            batches = [
                make_batch(zipf_ids(rng, (b, NNZ), vocab), 400 + i) for i in range(4)
            ]
            state = init_packed_state(model, jax.random.key(0))
            iters = max(4, (1 << 21) // b)
            state, rate = measure_rate(step, state, batches, iters, b)
            knee[str(b)] = round(rate, 1)
            del state, batches
        except Exception as e:
            knee[str(b)] = f"FAILED: {str(e)[:80]}"
    res["knee_dense_vocab16m_exs"] = knee

    # --- element vs row accumulator, dense update, interleaved ---
    b = 16384
    batches = [make_batch(zipf_ids(rng, (b, NNZ), vocab), 500 + i) for i in range(8)]
    step_e = make_packed_train_step(model, 0.01, "dense")
    step_r = make_packed_train_step(model, 0.01, "dense")
    st_e = init_packed_state(model, jax.random.key(0))
    st_r = init_packed_state(model, jax.random.key(0), accumulator="row")
    for s, st in ((step_e, st_e), (step_r, st_r)):
        st2, _ = s(st, batches[0])
        forced_sync(st2)
        if st is st_e:
            st_e = st2
        else:
            st_r = st2
    rates = {"element": [], "row": []}
    for _ in range(4):
        for name, s in (("element", step_e), ("row", step_r)):
            st = st_e if name == "element" else st_r
            t0 = time.perf_counter()
            for i in range(10):
                st, _ = s(st, batches[i % len(batches)])
            forced_sync(st)
            rates[name].append(b * 10 / (time.perf_counter() - t0))
            if name == "element":
                st_e = st
            else:
                st_r = st
    res["accum_dense_vocab16m_exs"] = {
        k: round(float(np.median(v)), 1) for k, v in rates.items()
    }
    del st_e, st_r, batches

    # --- vocab scale with row accumulator (the scale pairing) ---
    for vexp in (26, 27):
        v = 1 << vexp
        try:
            m = FMModel(vocabulary_size=v, factor_num=K, order=2)
            step = make_packed_train_step(m, 0.01, "dense")
            bt = [make_batch(zipf_ids(rng, (b, NNZ), v), 600 + i) for i in range(4)]
            st = init_packed_state(m, jax.random.key(0), accumulator="row")
            st, rate = measure_rate(step, st, bt, 10, b)
            res[f"dense_row_vocab2e{vexp}_exs"] = round(rate, 1)
            del st, bt, step
        except Exception as e:
            res[f"dense_row_vocab2e{vexp}_exs"] = f"FAILED: {str(e)[:80]}"

    _watchdog.cancel()


if __name__ == "__main__":
    main()
