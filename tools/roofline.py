#!/usr/bin/env python
"""Per-op roofline evidence for the scale-regime train step.

Answers two VERDICT-r2 questions with measurements, not prose:

1. Where does the 25 µs step actually go?  ``jax.profiler`` traces a few
   steps at the 235M-row regime and this script aggregates the device-side
   ("XLA Ops" thread) op durations — the itemized evidence behind the
   modeled-bytes keys bench.py emits.
2. Is "uniform ids faster than Zipf" a real effect or tunnel-window drift?
   The two id distributions run through the SAME executable in
   INTERLEAVED windows (Z/U/Z/U/...), so any window-scale drift hits both
   equally; the per-distribution spread vs the cross-distribution gap
   separates measurement noise from a physical effect.

Prints one JSON object; run on the real chip.  Results land in DESIGN §6.
"""

import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=2400, what="roofline.py")

import jax  # noqa: E402
import numpy as np  # noqa: E402

import bench as B  # noqa: E402  (reuses the ladder, batch maker, state builder)
from fast_tffm_tpu.models import FMModel  # noqa: E402
from fast_tffm_tpu.trainer import make_train_step  # noqa: E402


def window(step, state, batches, iters=20):
    """Marginal us/step, VALUE-SYNCED (bench.forced_sync): this round
    measured block_until_ready(loss) returning microseconds after a loop
    whose value-forced completion takes N x ~150 ms on this backend —
    every wall rate must close over a fetch that depends on the final
    table (DESIGN 6)."""
    t0 = time.perf_counter()
    for i in range(iters):
        state, loss = step(state, batches[i % len(batches)])
    B.forced_sync(state)
    return state, (time.perf_counter() - t0) / iters * 1e6  # us/step


def trace_steps(tag, step, state, batches, n=3):
    out_dir = f"/tmp/roofline_trace/{tag}"
    jax.profiler.start_trace(out_dir)
    for i in range(n):
        state, loss = step(state, batches[i % len(batches)])
    jax.block_until_ready(loss)
    jax.profiler.stop_trace()
    path = sorted(glob.glob(f"{out_dir}/plugins/profile/*/*.trace.json.gz"))[-1]
    d = json.loads(gzip.open(path).read())
    # Map (pid, tid) -> thread name, keep only the device "XLA Ops" rows.
    tids = {}
    for e in d.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e.get("pid"), e.get("tid"))] = e["args"]["name"]
    ops = {}
    for e in d.get("traceEvents", []):
        if e.get("ph") == "X" and tids.get((e.get("pid"), e.get("tid"))) == "XLA Ops":
            ops.setdefault(e["name"], [0.0, 0])
            ops[e["name"]][0] += e.get("dur", 0.0)
            ops[e["name"]][1] += 1
    total = sum(v[0] for v in ops.values())
    top = sorted(ops.items(), key=lambda kv: -kv[1][0])[:12]
    return state, {
        "per_step_device_us": round(total / max(n, 1), 1),
        "ops": [
            {"op": k[:70], "us_per_step": round(v[0] / n, 1), "calls": v[1]}
            for k, v in top
        ],
    }


def setup(vocab_ladder, rng):
    for cand in vocab_ladder:
        model = FMModel(vocabulary_size=cand, factor_num=B.SCALE_K, order=2)
        step = make_train_step(model, learning_rate=0.01)
        zipf = [
            B.make_batch(B.zipf_ids(rng, (B.BATCH, B.NNZ), cand), i)
            for i in range(8)
        ]
        try:
            state = B.scale_state(cand, B.SCALE_K)
            state, loss = step(state, zipf[0])
            jax.block_until_ready(loss)
            return cand, step, state, zipf
        except Exception as e:
            print(f"# rung {cand} failed: {str(e)[:90]}", file=sys.stderr)
    raise SystemExit("no rung compiled")


def main():
    rng = np.random.default_rng(0)
    out = {"batch": B.BATCH, "nnz": B.NNZ, "device": str(jax.devices()[0])}

    def emit():
        print(json.dumps(out, indent=1), flush=True)

    # Id-distribution statistics from HOST-side draws (fetching device
    # ids next to the full-HBM state OOMs the transfer staging buffer —
    # measured RESOURCE_EXHAUSTED).
    stat_rng = np.random.default_rng(123)
    out["unique_ids_per_batch"] = {
        "zipf": int(np.unique(B.zipf_ids(stat_rng, (B.BATCH, B.NNZ), B.SCALE_VOCABS[0])).size),
        "uniform": int(np.unique(stat_rng.integers(0, B.SCALE_VOCABS[0], (B.BATCH, B.NNZ))).size),
    }
    emit()

    # --- interleaved A/B at the LARGEST rung (the headline regime) ---
    vocab, step, state, zipf = setup(B.SCALE_VOCABS, rng)
    uni = [
        B.make_batch(rng.integers(0, vocab, size=(B.BATCH, B.NNZ)).astype(np.int32), 100 + i)
        for i in range(8)
    ]
    out["vocab"] = vocab
    state, _ = window(step, state, zipf, iters=30)  # warm both
    state, _ = window(step, state, uni, iters=30)
    inter = {"zipf": [], "uniform": []}
    for _ in range(5):
        state, us = window(step, state, zipf)
        inter["zipf"].append(round(us, 2))
        state, us = window(step, state, uni)
        inter["uniform"].append(round(us, 2))
    out["interleaved_us_per_step"] = inter
    emit()
    del state, step, zipf, uni

    # --- per-op traces at the 2^27 rung: the profiler needs HBM for its
    #     own buffers and OOMs next to the 8.9 GB headline state
    #     (measured); the step's op structure is identical, only the
    #     table rows differ. ---
    vocab_t, step, state, zipf = setup([1 << 27], rng)
    uni = [
        B.make_batch(rng.integers(0, vocab_t, size=(B.BATCH, B.NNZ)).astype(np.int32), 100 + i)
        for i in range(8)
    ]
    state, _ = window(step, state, zipf, iters=30)
    state, _ = window(step, state, uni, iters=30)
    out["trace_vocab"] = vocab_t
    for tag, bats in (("zipf", zipf), ("uniform", uni)):
        try:
            state, prof = trace_steps(f"{tag}_{vocab_t}", step, state, bats)
            out[f"profile_{tag}"] = prof
        except Exception as e:
            out[f"profile_{tag}"] = {"error": str(e)[:140]}
        emit()


if __name__ == "__main__":
    main()
