#!/usr/bin/env python
"""Thread-scaling benchmark of the C++ libsvm parser.

The reference fed its FmParser from `thread_num` queue-runner threads; here
the pool lives inside one GIL-released C++ call (csrc/libsvm_parser.cpp ::
parse_spans_mt).  A pod host drives 4-8 chips and needs multi-M rows/s of
text parse for the first pass (steady state streams FMB) — this script
measures rows/s/host at a sweep of thread counts so that claim is a number,
not a guess.

Usage: python tools/bench_parse.py [--rows 200000] [--nnz 39]
                                   [--threads 1,2,4,8] [--repeat 5]
Prints one JSON line per thread count and a summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synth_lines(rows: int, nnz: int, vocab: int, seed: int = 0) -> list[str]:
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(rows):
        ids = rng.integers(0, vocab, size=nnz)
        vals = rng.normal(size=nnz)
        toks = " ".join(f"{i}:{v:.4f}" for i, v in zip(ids, vals))
        out.append(f"{int(rng.integers(0, 2))} {toks}")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--nnz", type=int, default=39)
    ap.add_argument("--vocab", type=int, default=1 << 20)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--threads", default="1,2,4,8")
    ap.add_argument("--repeat", type=int, default=5)
    args = ap.parse_args()

    from fast_tffm_tpu.data.native import load_native_parser

    parser = load_native_parser()
    if parser is None:
        print(json.dumps({"error": "native parser unavailable (build failed?)"}))
        return 1

    lines = synth_lines(args.rows, args.nnz, args.vocab)
    batches = [
        lines[i : i + args.batch] for i in range(0, len(lines), args.batch)
    ]
    from fast_tffm_tpu.data.native import usable_cores

    cores = usable_cores()
    raw = [int(t) for t in args.threads.split(",")]
    if any(t < 0 for t in raw):
        print(json.dumps({"error": "negative thread counts are invalid"}))
        return 1
    # Same 0-means-all-cores resolution the config layer gets.
    sweep = sorted({(t if t > 0 else cores) for t in raw} | {cores})
    results = {}
    for t in sweep:
        parser.threads = t
        rates = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            for chunk in batches:
                parser(chunk, vocabulary_size=args.vocab, max_nnz=args.nnz)
            rates.append(args.rows / (time.perf_counter() - t0))
        results[t] = float(np.median(rates))
        print(
            json.dumps(
                {
                    "metric": "text parse rows/sec/host",
                    "threads": t,
                    "value": round(results[t], 1),
                    "host_cores": cores,
                    "nnz": args.nnz,
                }
            )
        )
    best = max(results.values())
    print(
        json.dumps(
            {
                "metric": "text parse rows/sec/host (best)",
                "value": round(best, 1),
                "host_cores": cores,
                "note": (
                    "thread scaling requires physical cores; this host has "
                    f"{cores} — see README input-pipeline notes"
                    if cores < max(results)
                    else "pool scales with cores"
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
