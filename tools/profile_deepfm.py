#!/usr/bin/env python
"""Profile the DeepFM (BASELINE config #4) step: where does the time go?

VERDICT r2 #7: cfg4 is the one BASELINE config where dense MXU work
(3×400 MLP) dominates, and no trace evidence existed that the matmuls are
near roofline.  This traces a few steps (f32 and bf16 compute_dtype),
aggregates device-op durations, and reports the MLP matmul share plus the
implied MXU utilization for the [B, N·k]×[N·k, 400] chain.

Prints one JSON object; run on the real chip.  Results land in DESIGN §6.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=1200, what="profile_deepfm.py")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from fast_tffm_tpu.models import Batch, DeepFMModel  # noqa: E402
from fast_tffm_tpu.trainer import init_state, make_train_step  # noqa: E402
from tools.roofline import trace_steps, window  # noqa: E402

VOCAB = 1 << 20
FIELDS = 39
K = 8
BATCH = 16384
HIDDEN = (400, 400, 400)


def mlp_flops_per_step():
    """Forward+backward matmul FLOPs for the MLP chain per step."""
    dims = [FIELDS * K, *HIDDEN, 1]
    fwd = sum(2 * BATCH * a * b for a, b in zip(dims[:-1], dims[1:]))
    return 3 * fwd  # bwd ~2x fwd for matmuls


def make_batch(rng, i):
    return Batch(
        labels=np.asarray(rng.integers(0, 2, BATCH), np.float32),
        ids=np.asarray(rng.integers(0, VOCAB, (BATCH, FIELDS)), np.int32),
        vals=np.abs(rng.normal(size=(BATCH, FIELDS)).astype(np.float32)) + 0.1,
        fields=np.tile(np.arange(FIELDS, dtype=np.int32), (BATCH, 1)),
        weights=np.ones(BATCH, np.float32),
    )


def run(compute_dtype):
    model = DeepFMModel(
        vocabulary_size=VOCAB, num_fields=FIELDS, factor_num=K,
        hidden_dims=HIDDEN, compute_dtype=compute_dtype,
    )
    step = make_train_step(model, 0.01)
    rng = np.random.default_rng(0)
    batches = [make_batch(rng, i) for i in range(8)]
    state = init_state(model, jax.random.key(0))
    state, us0 = window(step, state, batches, iters=5)  # compile+warm
    state, us = window(step, state, batches, iters=30)
    state, prof = trace_steps(f"deepfm_{compute_dtype}", step, state, batches)
    # Classify device ops: matmul/MXU vs rest.
    mm_us = sum(
        o["us_per_step"] for o in prof["ops"]
        if any(t in o["op"] for t in ("dot", "conv", "matmul", "fusion"))
        and any(t in o["op"] for t in ("dot", "matmul"))
    )
    flops = mlp_flops_per_step()
    peak = {"float32": 98.3e12 / 2, "bfloat16": 394e12 / 2}[compute_dtype]
    # v5e: 394 TFLOP/s bf16, ~1/4 for f32; /2 above is a conservative
    # de-rate for the small inner dims (312..400) vs the 128x128 MXU tile.
    return {
        "us_per_step_wall": round(us, 1),
        "examples_per_sec": round(BATCH / us * 1e6, 1),
        "device_profile": prof,
        "mlp_matmul_us_per_step": round(mm_us, 1),
        "mlp_matmul_share": round(
            mm_us / max(prof["per_step_device_us"], 1e-9), 3
        ),
        "mlp_flops_per_step": flops,
        "mfu_vs_derated_peak": round(flops / (mm_us * 1e-6) / peak, 3)
        if mm_us else None,
    }


def main():
    out = {"batch": BATCH, "fields": FIELDS, "k": K, "hidden": HIDDEN}
    for dt in ("float32", "bfloat16"):
        out[dt] = run(dt)
    out["device"] = str(jax.devices()[0])
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
