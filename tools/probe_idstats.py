#!/usr/bin/env python
"""Id-traffic statistics probe (ISSUE 9): dedup ratio + heavy-hitter
mass on the Zipf(1.1) scale workload, through the REAL telemetry path.

Runs a short streamed train over a synthesized Zipf(1.1) FMB (the bench
scale workload's id distribution: nnz=39, binary labels) with
``datastats_every_steps`` on, then summarizes the committed numbers
ROADMAP item 3 sizes its two levers against:

  * **dedup-before-gather** — per-batch ``dedup_ratio`` (unique/slots):
    the forward gather re-reads each hot row 1/dedup times; the
    projected byte saving per step is ``(1 - dedup) * gather_bytes``.
  * **hot-id cache K** — the sketch's top-K bucket mass (upper bound)
    NEXT TO the exact host-side coverage curve (bincount over the whole
    dataset): the fraction of gather traffic a top-K resident cache
    absorbs, for K across the ladder.  The sketch-vs-exact column is the
    sketch's accuracy receipt.

The run also emits the kind=profile measured-vs-modeled ledger, which
the probe copies in — measured bytes next to the modeled floor for the
same dispatch.  Writes PROBE_IDSTATS_r09.json (stamped with the run's
telemetry run_id + schema_version).

Usage:
  python tools/probe_idstats.py [--batch 65536] [--rows 524288]
      [--vocab 4194304] [--out PROBE_IDSTATS_r09.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fast_tffm_tpu.telemetry import (
    arm_hang_exit,
    artifact_stamp,
    new_run_id,
    write_json_artifact,
)

_watchdog = arm_hang_exit(seconds=3000, what="probe_idstats.py")

import numpy as np  # noqa: E402

NNZ = 39  # the bench scale workload's row width


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--rows", type=int, default=1 << 19)
    ap.add_argument("--vocab", type=int, default=1 << 22)
    ap.add_argument("--factor-num", type=int, default=8)
    ap.add_argument("--every", type=int, default=1, help="datastats sample cadence")
    ap.add_argument("--hh-k", type=int, default=16)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default=os.path.join(REPO, "PROBE_IDSTATS_r09.json"))
    args = ap.parse_args(argv)

    from bench import ensure_scale_fmb  # synthesizes/caches the Zipf FMB

    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.data.binary import open_fmb
    from fast_tffm_tpu.training import train

    fmb = ensure_scale_fmb(args.vocab, rows=args.rows, seed=args.seed)
    run_id = new_run_id()
    row_dim = 1 + args.factor_num
    with tempfile.TemporaryDirectory(prefix="idstats-") as d:
        metrics = os.path.join(d, "run.jsonl")
        cfg = Config(
            model="fm",
            factor_num=args.factor_num,
            vocabulary_size=args.vocab,
            hash_feature_id=True,  # bench's synthetic FMB is written hashed
            model_file=os.path.join(d, "m.npz"),
            train_files=(fmb,),
            epoch_num=1,
            batch_size=args.batch,
            max_nnz=NNZ,
            learning_rate=0.05,
            log_every=4,
            adagrad_accumulator="row",  # the scale ladder's accumulator
            metrics_path=metrics,
            telemetry_run_id=run_id,
            telemetry_datastats_every_steps=args.every,
            telemetry_heavy_hitter_k=args.hh_k,
            save_every_epochs=0,
        ).validate()
        t0 = time.time()
        train(cfg, log=lambda *a: print(*a, file=sys.stderr))
        wall = time.time() - t0
        records = [json.loads(l) for l in open(metrics) if l.strip()]

    ds = [r for r in records if r["kind"] == "datastats"]
    prof = [
        r for r in records if r["kind"] == "profile" and r["program"] == "train_step"
    ]
    steady = [r for r in records if r["kind"] == "compile" and not r["warmup"]]
    if not ds:
        print("probe_idstats: no datastats records — nothing to commit", file=sys.stderr)
        return 1
    dedup = float(np.mean([r["dedup_ratio"] for r in ds]))
    uniq_mean = float(np.mean([r["unique"] for r in ds]))
    gather_bytes = ds[-1]["gather_bytes"]  # per sampled dispatch (static shape)

    # Exact hot-id coverage from the dataset itself (the histogram the
    # sketch approximates): fraction of all gather slots a top-K resident
    # cache absorbs.  bincount over the vocab is host-cheap at probe scale.
    f = open_fmb(fmb)
    ids = np.asarray(f.ids).reshape(-1)
    counts = np.bincount(ids, minlength=args.vocab)
    order = np.sort(counts)[::-1]
    csum = np.cumsum(order, dtype=np.float64)
    total = float(csum[-1])
    coverage = {
        str(k): round(float(csum[min(k, csum.size) - 1] / total), 4)
        for k in (256, 4096, 65536, 1 << 20)
        if k <= args.vocab
    }
    exact_topk_mass = round(float(csum[min(args.hh_k, csum.size) - 1] / total), 4)

    result = {
        "probe": "PROBE_IDSTATS",
        **artifact_stamp(run_id),
        "workload": {
            "distribution": "zipf_1.1",
            "batch": args.batch,
            "nnz": NNZ,
            "rows": args.rows,
            "vocab": args.vocab,
            "row_dim": row_dim,
            "samples": len(ds),
            "wall_s": round(wall, 1),
        },
        "dedup_ratio_mean": round(dedup, 4),
        "unique_ids_per_batch_mean": round(uniq_mean, 1),
        "gather_bytes_per_step": gather_bytes,
        "dedup_gather_bytes_per_step": int(round(uniq_mean)) * row_dim * 4,
        "projected_gather_savings_frac": round(1.0 - dedup, 4),
        "projected_gather_savings_bytes_per_step": int(
            round((1.0 - dedup) * gather_bytes)
        ),
        "hh_k": args.hh_k,
        "hh_topk_mass_sketch": ds[-1]["hh_topk_mass"],
        "hh_topk_mass_exact": exact_topk_mass,
        "hot_id_cache_coverage_exact": coverage,
        "rows_seen": ds[-1]["rows_seen"],
        "rows_seen_frac": ds[-1]["rows_seen_frac"],
        "measured_train_step": (
            {
                k: prof[-1].get(k)
                for k in (
                    "bytes_accessed", "modeled_hbm_bytes", "bytes_per_example",
                    "flops", "examples",
                )
            }
            if prof
            else None
        ),
        "steady_state_recompiles": len(steady),
        "note": (
            "dedup_ratio = unique/slots per dispatch (padding slots are "
            "real gather traffic and dedup to one row); sketch mass is an "
            "upper bound on exact top-K id mass (bucket collisions merge "
            "ids) — the exact column is the receipt.  "
            "hot_id_cache_coverage_exact[K] = fraction of gather slots a "
            "top-K resident cache absorbs (ROADMAP item 3's K)."
        ),
    }
    out = json.dumps(result, indent=1, sort_keys=True)
    print(out)
    write_json_artifact(args.out, result)
    print(f"probe -> {args.out}", file=sys.stderr)
    _watchdog.cancel()
    return 0 if not steady else 1


if __name__ == "__main__":
    sys.exit(main())
