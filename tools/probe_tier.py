#!/usr/bin/env python
"""Beyond-HBM tiered-store probe (ISSUE 12): train a vocab far past the
device wall (default 2^30 rows — 4x the 2^28 single-chip ceiling DESIGN
§8.6 measured) on ONE chip through the [ParamStore] tiered path, and pin
the residency economics against the PR-9 evidence:

  * **hit rate vs coverage curve** — the measured hot-tier hit rate
    (kind=tiering telemetry) next to the EXACT coverage a top-K cache
    should absorb on this workload (host bincount over every gather
    slot — the same curve PROBE_IDSTATS_r09 committed at the 2^22 scale
    shape, where top-4096 absorbed 59%).  The acceptance bar: measured
    within a few points of predicted (the sample-policy hot set is drawn
    from a prefix, the curve from the whole stream).
  * **gather savings** — the CostLedger's measured bytes/example for the
    compiled tiered step next to the resident path's modeled floor, plus
    the wire/staging bytes the dedup + hit path actually shipped.

The workload is bench.py's Zipf(1.1) scale shape (NNZ=39, synthesized
FMB via ensure_scale_fmb).  Also reachable as `python bench.py --tier`.

Usage:
  python tools/probe_tier.py [--vocab 1073741824] [--batch 4096]
      [--steps 12] [--hot 4096] [--out PROBE_TIER_r12.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fast_tffm_tpu.telemetry import (
    arm_hang_exit,
    artifact_stamp,
    new_run_id,
    write_json_artifact,
)

_watchdog = arm_hang_exit(seconds=3000, what="probe_tier.py")

import numpy as np  # noqa: E402


def _read_jsonl(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    return [json.loads(l) for l in open(path) if l.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--vocab", type=int, default=1 << 30)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--hot", type=int, default=4096)
    ap.add_argument("--factor-num", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--delta-every", type=int, default=4)
    ap.add_argument("--out", default=os.path.join(REPO, "PROBE_TIER_r12.json"))
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench  # repo-root module: the scale workload's one source of truth
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.data.binary import open_fmb
    from fast_tffm_tpu.training import train

    rows = args.batch * args.steps
    t0 = time.time()
    fmb = bench.ensure_scale_fmb(args.vocab, rows=rows, seed=args.seed)

    # Exact coverage curve over EVERY gather slot of the workload (the
    # prediction the measured hit rate is pinned against).
    ids = np.asarray(open_fmb(fmb).ids[:rows]).reshape(-1)
    uniq, cnt = np.unique(ids, return_counts=True)
    order = np.argsort(-cnt, kind="stable")
    csum = np.cumsum(cnt[order])
    total_slots = ids.size

    def coverage(k: int) -> float:
        k = min(k, csum.size)
        return float(csum[k - 1] / total_slots) if k else 0.0

    curve = {str(k): round(coverage(k), 4) for k in (256, 4096, 65536)}
    predicted = coverage(args.hot)

    work = tempfile.mkdtemp(prefix="probe_tier_")
    run_id = new_run_id()
    metrics = os.path.join(work, "metrics.jsonl")
    cfg = Config()
    cfg.model = "fm"
    cfg.factor_num = args.factor_num
    cfg.vocabulary_size = args.vocab
    cfg.hash_feature_id = True  # ensure_scale_fmb writes pre-hashed ids
    cfg.train_files = (fmb,)
    cfg.max_nnz = bench.NNZ
    cfg.epoch_num = 1
    cfg.batch_size = args.batch
    cfg.learning_rate = 0.05
    cfg.log_every = max(1, args.steps // 4)
    cfg.model_file = os.path.join(work, "model.ckpt")
    cfg.metrics_path = metrics
    cfg.telemetry_run_id = run_id
    cfg.save_every_epochs = 1
    cfg.delta_every_steps = args.delta_every
    # Row-granular accumulator: the cold store's accumulator file packs
    # 1024 rows per page instead of one row per ~9-lane stripe — at
    # 2^30 sparse-file scale that halves the probe's dirty-page bill.
    cfg.adagrad_accumulator = "row"
    cfg.paramstore = True
    cfg.paramstore_hot_rows = args.hot
    cfg.paramstore_dir = os.path.join(work, "store")
    cfg.paramstore_residency = "sample"
    cfg.paramstore_sample_batches = min(8, args.steps)
    cfg.validate()

    logs: list[str] = []
    train(cfg, log=lambda *a: logs.append(" ".join(map(str, a))))
    wall = time.time() - t0

    recs = _read_jsonl(metrics)
    tier = [r for r in recs if r.get("kind") == "tiering"]
    if not tier:
        print("no kind=tiering records emitted — probe failed", file=sys.stderr)
        return 1
    hits = [r["hit_rate"] for r in tier]
    # Weighted by miss exposure windows — simple mean is fine at this
    # probe's uniform window sizes.
    hit_rate = round(sum(hits) / len(hits), 4)
    dedups = [r["dedup_ratio"] for r in tier if r.get("dedup_ratio") is not None]
    miss_bytes = int(np.median([r["miss_bytes_per_step"] for r in tier]))
    wire_bytes = int(np.median([r["wire_bytes_per_step"] for r in tier]))
    steady = sum(
        r.get("compiles", 0)
        for r in recs
        if r.get("kind") == "compile" and not r.get("warmup")
    )
    prof = [
        r
        for r in recs
        if r.get("kind") == "profile" and r.get("program") == "train_step"
    ]
    measured = (
        {
            k: prof[-1].get(k)
            for k in (
                "bytes_accessed", "flops", "examples", "bytes_per_example",
                "modeled_hbm_bytes",
            )
        }
        if prof
        else None
    )

    # The PR-9 committed curve (2^22 scale shape) as the cross-scale
    # reference the ISSUE names.
    pr9 = None
    pr9_path = os.path.join(REPO, "PROBE_IDSTATS_r09.json")
    if os.path.exists(pr9_path):
        with open(pr9_path) as f:
            pr9 = json.load(f).get("hot_id_cache_coverage_exact")

    # The resident path at this vocab would need ~vocab*(D+A)*4 bytes of
    # device memory — report the wall it walked past.
    d = args.factor_num + 1
    resident_bytes = args.vocab * (d + 1) * 4

    out = {
        "probe": "PROBE_TIER",
        **artifact_stamp(run_id),
        "workload": {
            "vocab": args.vocab,
            "batch": args.batch,
            "steps": args.steps,
            "nnz": bench.NNZ,
            "row_dim": d,
            "rows": rows,
            "distribution": "zipf_1.1",
            "wall_s": round(wall, 1),
        },
        "hot_rows": args.hot,
        "hit_rate_measured": hit_rate,
        "hit_rate_predicted_exact": round(predicted, 4),
        "hit_rate_gap": round(abs(hit_rate - predicted), 4),
        "coverage_curve_exact": curve,
        "pr9_coverage_curve_2e22": pr9,
        "dedup_ratio_mean": round(sum(dedups) / len(dedups), 4) if dedups else None,
        "miss_bytes_per_step": miss_bytes,
        "wire_bytes_per_step": wire_bytes,
        "resident_state_bytes_this_vocab": resident_bytes,
        "device_tier_rows": args.hot,
        "measured_train_step": measured,
        "steady_state_recompiles": steady,
        "note": (
            "hit_rate_measured = hot-tier share of gather slots over the "
            "run (kind=tiering); hit_rate_predicted_exact = exact top-"
            f"{args.hot} coverage of this workload's slot distribution "
            "(the PR-9 curve recomputed at this scale) — the sample-"
            "policy hot set is drawn from a stream prefix, so a few "
            "points of gap is the expected sampling error.  "
            "resident_state_bytes_this_vocab is what a non-tiered run "
            "would need on device (vs the ~11.5 GB single-chip wall)."
        ),
    }
    write_json_artifact(args.out, out)
    shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    rc = main()
    _watchdog.cancel()
    sys.exit(rc)
