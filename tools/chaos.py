#!/usr/bin/env python
"""Chaos probe (ISSUE 6): supervised crash-and-resume MTTR + loss parity.

Drives the resilience layer end to end with REAL trainer subprocesses:

  baseline   one uninterrupted run per path (streamed, sharded) on a
             seeded synthetic CTR set, per-step losses logged to JSONL.
  chaos      the same run under ``train --supervised`` with a seeded
             ``kill@N`` fault plan: the child SIGKILLs itself at step N,
             the supervisor relaunches it with ``--resume``, and the
             resumed child reopens the input at the checkpoint cursor.

For every trial the probe checks the acceptance pin — each step of the
uninterrupted run appears in the chaos run's concatenated log with a
bit-identical loss — and records the supervisor's measured MTTR
(crash → first new training progress in the relaunched child, backoff
included: that IS recovery time the fleet pays).

The kill steps are drawn from ``random.Random(seed)``, so a probe run is
reproducible bit for bit (the fault plan's byte-identity is separately
pinned by tests/test_resilience.py).

Writes PROBE_MTTR_r06.json; ``--processes 2`` chaoses the REAL
multi-process pod instead (dist_train under the pod supervisor, gloo
CPU collectives, one SIGKILLed host per trial, victims alternating
writer/survivor) and writes PROBE_MTTR_DIST_r07.json.  Usage:
  python tools/chaos.py [--trials 3] [--seed 1106] [--sharded]
                        [--processes 2] [--out PROBE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROWS = 320
BATCH = 32
EPOCHS = 2
STEPS = ROWS // BATCH * EPOCHS  # 20
DELTA_EVERY = 3


def _write_dataset(path: str) -> None:
    import numpy as np

    rng = np.random.default_rng(7)
    lines = []
    for _ in range(ROWS):
        ids = rng.choice(64, size=4, replace=False)
        toks = " ".join(f"{i}:1.0" for i in ids)
        lines.append(f"{rng.integers(0, 2)} {toks}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _write_cfg(d: str, processes: int = 1) -> str:
    cfg = os.path.join(d, "run.cfg")
    dist = (
        f"\n[Distributed]\nnum_processes = {processes}\nbarrier_timeout_s = 60\n"
        if processes > 1
        else ""
    )
    with open(cfg, "w") as f:
        f.write(
            f"""
[General]
model = fm
factor_num = 4
vocabulary_size = 64
model_file = {d}/m.ckpt

[Checkpoint]
delta_every_steps = {DELTA_EVERY}

[Train]
train_files = {d}/t.libsvm
epoch_num = {EPOCHS}
batch_size = {BATCH}
max_nnz = 4
learning_rate = 0.1
log_every = 1
metrics_path = {d}/run.jsonl
{dist}"""
        )
    return cfg


def _env(processes: int = 1) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if processes > 1:
        # One virtual device per pod host: the mesh spans the processes.
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        return env
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return env


def _run(
    mode: str, cfg: str, *args, timeout: int = 600, processes: int = 1
) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "fast_tffm.py"), mode, cfg, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_env(processes),
        cwd=REPO,
        timeout=timeout,
    )


def _records(path: str, kind: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == kind:
                out.append(r)
    return out


def _losses(path: str) -> dict[int, float]:
    """step -> LAST logged loss (replayed steps re-log; the last feeds
    the surviving state)."""
    return {r["step"]: r["loss"] for r in _records(path, "train")}


def _trial(
    mode: str,
    kill_at: int,
    base_losses: dict[int, float],
    processes: int = 1,
    victim: int = 0,
) -> dict:
    """One supervised chaos run; returns the trial record.  Pod runs
    (``processes`` > 1) SIGKILL host ``victim`` — alternating writer /
    non-writer across trials exercises both halves of the single-host
    relaunch protocol."""
    with tempfile.TemporaryDirectory(prefix="chaos-") as d:
        _write_dataset(os.path.join(d, "t.libsvm"))
        cfg = _write_cfg(d, processes)
        extra = (
            ["--fault-process", str(victim)] if processes > 1 else []
        )
        t0 = time.monotonic()
        proc = _run(
            mode, cfg, "--supervised", "--fault-plan", f"kill@{kill_at}",
            "--max-restarts", "3", *extra, processes=processes,
        )
        wall_s = time.monotonic() - t0
        metrics = os.path.join(d, "run.jsonl")
        out: dict = {
            "mode": mode,
            "kill_at_step": kill_at,
            "supervisor_rc": proc.returncode,
            "wall_s": round(wall_s, 3),
        }
        if processes > 1:
            out["processes"] = processes
            out["victim"] = victim
        if proc.returncode != 0:
            out["error"] = proc.stdout[-2000:]
            return out
        got = _losses(metrics)
        missing = sorted(set(base_losses) - set(got))
        mismatched = sorted(
            s for s, v in base_losses.items() if s in got and got[s] != v
        )
        faults = [
            r for r in _records(metrics, "fault") if r.get("event") == "crash"
        ]
        restarts = _records(metrics, "restart")
        # Save boundaries: every DELTA_EVERY steps plus the epoch ends —
        # the resumed child replays kill_at minus the last one before it.
        boundaries = set(range(DELTA_EVERY, STEPS + 1, DELTA_EVERY))
        boundaries.update(range(STEPS // EPOCHS, STEPS + 1, STEPS // EPOCHS))
        last_save = max((s for s in boundaries if s <= kill_at), default=0)
        out.update(
            losses_bit_identical=not missing and not mismatched,
            missing_steps=missing,
            mismatched_steps=mismatched,
            crashes=len(faults),
            restarts=len(restarts),
            replayed_steps=max(0, kill_at - last_save),
            mttr_s=[r.get("mttr_s") for r in restarts],
        )
        return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trials", type=int, default=3, metavar="N",
                    help="chaos trials per path (seeded kill steps)")
    ap.add_argument("--seed", type=int, default=1106)
    ap.add_argument("--sharded", action="store_true",
                    help="also run the dist_train (8-device CPU mesh) path")
    ap.add_argument("--processes", type=int, default=1, metavar="N",
                    help="N > 1: chaos the REAL multi-process pod instead "
                    "(dist_train under the pod supervisor, gloo CPU; each "
                    "trial SIGKILLs one host — victims alternate between "
                    "the writer and a survivor)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    pod = args.processes > 1
    out_path = args.out or os.path.join(
        REPO, "PROBE_MTTR_DIST_r07.json" if pod else "PROBE_MTTR_r06.json"
    )

    rng = random.Random(args.seed)
    modes = (
        ["dist_train"]
        if pod
        else ["train"] + (["dist_train"] if args.sharded else [])
    )
    result: dict = {
        "steps_total": STEPS,
        "delta_every_steps": DELTA_EVERY,
        "seed": args.seed,
        "paths": {},
    }
    if pod:
        result["processes"] = args.processes
    ok = True
    for mode in modes:
        with tempfile.TemporaryDirectory(prefix="chaos-base-") as d:
            _write_dataset(os.path.join(d, "t.libsvm"))
            t0 = time.monotonic()
            proc = _run(
                mode, _write_cfg(d, args.processes),
                *(["--supervised"] if pod else []),
                processes=args.processes,
            )
            if proc.returncode != 0:
                print(proc.stdout[-2000:], file=sys.stderr)
                print(f"chaos: {mode} baseline failed rc={proc.returncode}",
                      file=sys.stderr)
                return 1
            base_wall = time.monotonic() - t0
            base_losses = _losses(os.path.join(d, "run.jsonl"))
        assert len(base_losses) == STEPS, (
            f"baseline logged {len(base_losses)} steps, wanted {STEPS}"
        )
        trials = []
        for i in range(max(1, args.trials)):
            kill_at = rng.randrange(4, STEPS - 3)
            victim = i % args.processes if pod else 0
            label = f" victim=host{victim}" if pod else ""
            print(f"chaos: {mode} kill@{kill_at}{label} ...", flush=True)
            trials.append(
                _trial(mode, kill_at, base_losses,
                       processes=args.processes, victim=victim)
            )
        mttrs = [
            m for t in trials for m in t.get("mttr_s", [])
            if isinstance(m, (int, float))
        ]
        path_ok = all(
            t.get("supervisor_rc") == 0 and t.get("losses_bit_identical")
            for t in trials
        )
        ok = ok and path_ok
        result["paths"][mode] = {
            "baseline_wall_s": round(base_wall, 3),
            "trials": trials,
            "mttr_s_median": round(statistics.median(mttrs), 3) if mttrs else None,
            "mttr_s_max": round(max(mttrs), 3) if mttrs else None,
            "all_losses_bit_identical": path_ok,
        }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"chaos: wrote {out_path} (ok={ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
