#!/usr/bin/env python
"""Chaos probe (ISSUE 6): supervised crash-and-resume MTTR + loss parity.

Drives the resilience layer end to end with REAL trainer subprocesses:

  baseline   one uninterrupted run per path (streamed, sharded) on a
             seeded synthetic CTR set, per-step losses logged to JSONL.
  chaos      the same run under ``train --supervised`` with a seeded
             ``kill@N`` fault plan: the child SIGKILLs itself at step N,
             the supervisor relaunches it with ``--resume``, and the
             resumed child reopens the input at the checkpoint cursor.

For every trial the probe checks the acceptance pin — each step of the
uninterrupted run appears in the chaos run's concatenated log with a
bit-identical loss — and records the supervisor's measured MTTR
(crash → first new training progress in the relaunched child, backoff
included: that IS recovery time the fleet pays).

The kill steps are drawn from ``random.Random(seed)``, so a probe run is
reproducible bit for bit (the fault plan's byte-identity is separately
pinned by tests/test_resilience.py).

Writes PROBE_MTTR_r06.json; ``--processes 2`` chaoses the REAL
multi-process pod instead (dist_train under the pod supervisor, gloo
CPU collectives, one SIGKILLed host per trial, victims alternating
writer/survivor) and writes PROBE_MTTR_DIST_r07.json.

``--serve`` (ISSUE 8) chaoses the SERVING tier instead: a live
2-replica socket front end under a FaultPlan serving schedule
(``replica_kill@N`` SIGKILLs replica N, ``replica_slow@N:MS`` injects
per-flush latency, ``reload_corrupt@N`` corrupts the checkpoint under
the reload watcher's nose and then heals it), while a steady request
stream pins the acceptance: ZERO hung or unanswered clients (every
request gets a score or a typed code), every DELIVERED score
bit-identical to a fault-free baseline run of the same request set,
replica restart MTTR measured, and zero steady-state recompiles on
every replica.  Since PR 16 the DATA path rides the binary frame wire
pinned to a replica (serving/client.py FrameConnection) — killing the
pinned replica exercises the client's retry-once-on-peer failover —
while ops stay JSONL through the front end.  Writes
PROBE_SERVE_CHAOS_r16.json.

Usage:
  python tools/chaos.py [--trials 3] [--seed 1106] [--sharded]
                        [--processes 2] [--out PROBE.json]
  python tools/chaos.py --serve [--serve-plan SPEC] [--out PROBE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROWS = 320
BATCH = 32
EPOCHS = 2
STEPS = ROWS // BATCH * EPOCHS  # 20
DELTA_EVERY = 3


def _write_dataset(path: str) -> None:
    import numpy as np

    rng = np.random.default_rng(7)
    lines = []
    for _ in range(ROWS):
        ids = rng.choice(64, size=4, replace=False)
        toks = " ".join(f"{i}:1.0" for i in ids)
        lines.append(f"{rng.integers(0, 2)} {toks}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _write_cfg(d: str, processes: int = 1) -> str:
    cfg = os.path.join(d, "run.cfg")
    dist = (
        f"\n[Distributed]\nnum_processes = {processes}\nbarrier_timeout_s = 60\n"
        if processes > 1
        else ""
    )
    with open(cfg, "w") as f:
        f.write(
            f"""
[General]
model = fm
factor_num = 4
vocabulary_size = 64
model_file = {d}/m.ckpt

[Checkpoint]
delta_every_steps = {DELTA_EVERY}

[Train]
train_files = {d}/t.libsvm
epoch_num = {EPOCHS}
batch_size = {BATCH}
max_nnz = 4
learning_rate = 0.1
log_every = 1
metrics_path = {d}/run.jsonl
{dist}"""
        )
    return cfg


def _env(processes: int = 1) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if processes > 1:
        # One virtual device per pod host: the mesh spans the processes.
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        return env
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return env


def _run(
    mode: str, cfg: str, *args, timeout: int = 600, processes: int = 1
) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "fast_tffm.py"), mode, cfg, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_env(processes),
        cwd=REPO,
        timeout=timeout,
    )


def _records(path: str, kind: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == kind:
                out.append(r)
    return out


def _losses(path: str) -> dict[int, float]:
    """step -> LAST logged loss (replayed steps re-log; the last feeds
    the surviving state)."""
    return {r["step"]: r["loss"] for r in _records(path, "train")}


def _trial(
    mode: str,
    kill_at: int,
    base_losses: dict[int, float],
    processes: int = 1,
    victim: int = 0,
) -> dict:
    """One supervised chaos run; returns the trial record.  Pod runs
    (``processes`` > 1) SIGKILL host ``victim`` — alternating writer /
    non-writer across trials exercises both halves of the single-host
    relaunch protocol."""
    with tempfile.TemporaryDirectory(prefix="chaos-") as d:
        _write_dataset(os.path.join(d, "t.libsvm"))
        cfg = _write_cfg(d, processes)
        extra = (
            ["--fault-process", str(victim)] if processes > 1 else []
        )
        t0 = time.monotonic()
        proc = _run(
            mode, cfg, "--supervised", "--fault-plan", f"kill@{kill_at}",
            "--max-restarts", "3", *extra, processes=processes,
        )
        wall_s = time.monotonic() - t0
        metrics = os.path.join(d, "run.jsonl")
        out: dict = {
            "mode": mode,
            "kill_at_step": kill_at,
            "supervisor_rc": proc.returncode,
            "wall_s": round(wall_s, 3),
        }
        if processes > 1:
            out["processes"] = processes
            out["victim"] = victim
        if proc.returncode != 0:
            out["error"] = proc.stdout[-2000:]
            return out
        got = _losses(metrics)
        missing = sorted(set(base_losses) - set(got))
        mismatched = sorted(
            s for s, v in base_losses.items() if s in got and got[s] != v
        )
        faults = [
            r for r in _records(metrics, "fault") if r.get("event") == "crash"
        ]
        restarts = _records(metrics, "restart")
        # Save boundaries: every DELTA_EVERY steps plus the epoch ends —
        # the resumed child replays kill_at minus the last one before it.
        boundaries = set(range(DELTA_EVERY, STEPS + 1, DELTA_EVERY))
        boundaries.update(range(STEPS // EPOCHS, STEPS + 1, STEPS // EPOCHS))
        last_save = max((s for s in boundaries if s <= kill_at), default=0)
        out.update(
            losses_bit_identical=not missing and not mismatched,
            missing_steps=missing,
            mismatched_steps=mismatched,
            crashes=len(faults),
            restarts=len(restarts),
            replayed_steps=max(0, kill_at - last_save),
            mttr_s=[r.get("mttr_s") for r in restarts],
        )
        return out


# ---------------------------------------------------------------------------
# serving chaos (--serve): live front end + replica kill/slow/corrupt
# ---------------------------------------------------------------------------

SERVE_REPLICAS = 2
SERVE_REQUESTS = 600
SERVE_QPS = 200.0


def _serve_cfg(d: str, run_id: str = "") -> str:
    cfg = os.path.join(d, "serve.cfg")
    with open(cfg, "w") as f:
        f.write(
            f"""
[General]
model = fm
factor_num = 4
vocabulary_size = 4096
model_file = {d}/m.ckpt

[Train]
max_nnz = 6
metrics_path = {d}/serve.jsonl

[Telemetry]
run_id = {run_id}

[Serving]
buckets = 1 8 64
flush_deadline_ms = 3
replicas = {SERVE_REPLICAS}
classes = gold:2,std:1
reload_interval_s = 0.2
"""
        )
    return cfg


def _serve_checkpoint(model_file: str) -> bytes:
    """Write the serving checkpoint; returns the bytes of a CORRUPT
    would-be successor (different step, valid zip metadata, torn array
    data) — what a dying trainer's non-atomic publish leaves behind.
    Its signature and save_id still read, so the reload path ATTEMPTS
    the restore and must survive the CRC failure."""
    import jax

    from fast_tffm_tpu.checkpoint import save_checkpoint
    from fast_tffm_tpu.config import Config, build_model
    from fast_tffm_tpu.trainer import init_state

    cfg = Config(
        model="fm", factor_num=4, vocabulary_size=4096, max_nnz=6,
        model_file=model_file,
    ).validate()
    state = init_state(
        build_model(cfg), jax.random.key(3), cfg.init_accumulator_value
    )
    save_checkpoint(model_file, state._replace(table=state.table + 0.25))
    succ = model_file + ".successor"
    save_checkpoint(
        succ, state._replace(table=state.table + 0.5, step=state.step + 10)
    )
    with open(succ, "rb") as f:
        b = f.read()
    os.remove(succ)
    mid = len(b) // 2
    return b[:mid] + b"\xde\xad" * 32 + b[mid + 64:]


def _serve_lines(n: int, seed: int) -> list[str]:
    import numpy as np

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(rng.integers(1, 7))
        ids = rng.choice(4096, size=k, replace=False)
        vals = np.round(np.abs(rng.normal(size=k)) + 0.1, 4)
        out.append(
            f"{int(rng.integers(0, 2))} "
            + " ".join(f"{i}:{v}" for i, v in zip(ids, vals))
        )
    return out


def _client(port):
    """JSONL CONTROL connection to the front end (stats/ping/slow) — ops
    stay on the line protocol; only the DATA path rides frames."""
    from fast_tffm_tpu.serving.client import ServeConnection

    return ServeConnection(port)


def _parse_serve_lines(lines):
    from fast_tffm_tpu.data.libsvm import parse_lines

    return parse_lines(lines, vocabulary_size=4096, max_nnz=6)


def _drive_frames(fc, parsed, base: int, qps: float, events=None):
    """Send every row (req_ids base+i) as a 1-row binary REQUEST frame
    at ~qps; fire ``events`` (callables keyed by send-index) along the
    way — the chaos schedule rides the request stream, so faults land
    mid-traffic.  1-row frames keep the schedule at request granularity
    AND exercise the failover resend path per request."""
    import numpy as np

    events = events or {}
    interval = 1.0 / qps
    t_next = time.perf_counter()
    for i in range(parsed.batch_size):
        if i in events:
            events[i]()
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        t_next += interval
        klass = "gold" if i % 10 == 0 else "std"
        fc.send_batch(
            np.array([base + i], np.uint32),
            parsed.ids[i : i + 1],
            parsed.vals[i : i + 1],
            fields=parsed.fields[i : i + 1] if fc.uses_fields else None,
            klass=klass,
        )


def _serve_chaos(args) -> int:
    from fast_tffm_tpu.resilience import FaultPlan

    out_path = args.out or os.path.join(REPO, "PROBE_SERVE_CHAOS_r16.json")
    plan = FaultPlan.parse(args.serve_plan, seed=args.seed)
    serving = plan.serving_events()
    if not serving:
        print("chaos: --serve-plan has no serving faults", file=sys.stderr)
        return 1
    lines = _serve_lines(SERVE_REQUESTS, args.seed)
    from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact

    result: dict = {
        "probe": "SERVE_CHAOS",
        # Envelope join keys (run_id + schema_version): this probe is
        # joinable to the telemetry JSONL its serve tier wrote.
        **artifact_stamp(),
        "seed": args.seed,
        "plan": json.loads(plan.to_json()),
        "replicas": SERVE_REPLICAS,
        "requests": SERVE_REQUESTS,
        "qps": SERVE_QPS,
    }
    with tempfile.TemporaryDirectory(prefix="chaos-serve-") as d:
        # The tier adopts the probe's run_id (written into [Telemetry]),
        # so the stamp above genuinely joins this JSON to its JSONL.
        cfg_path = _serve_cfg(d, run_id=result["run_id"])
        model_file = os.path.join(d, "m.ckpt")
        corrupt_bytes = _serve_checkpoint(model_file)
        with open(model_file, "rb") as f:
            good_bytes = f.read()

        from fast_tffm_tpu.serving.client import FrameConnection, spawn_serve

        parsed = _parse_serve_lines(lines)

        # ---- baseline: fault-free, same request set --------------------
        proc, port = spawn_serve(cfg_path)
        try:
            fc = FrameConnection(port)
            _drive_frames(fc, parsed, base=0, qps=SERVE_QPS)
            missing = fc.wait_answered(range(len(lines)), timeout=60)
            assert not missing, f"baseline left {len(missing)} unanswered"
            with fc.lock:
                baseline = {
                    i: (fc.results[i][1] if fc.results[i][0] == "ok" else None)
                    for i in range(len(lines))
                }
            fc.close()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()  # a child ignoring SIGTERM must not survive
                proc.wait(timeout=5)  # reap: its port must be free below
        unscored = sum(1 for v in baseline.values() if v is None)
        result["baseline_unscored"] = unscored
        if unscored:
            print(f"chaos: baseline failed to score {unscored} requests",
                  file=sys.stderr)

        # ---- chaos run: same lines, faults mid-stream ------------------
        proc, port = spawn_serve(cfg_path)
        hard_fail = None
        try:
            client = _client(port)  # CONTROL (JSONL): stats/ping/slow
            fc = FrameConnection(port)  # DATA (binary, replica-pinned)
            result["wire"] = "binary"
            result["pinned_replica"] = fc.replica
            stats0 = client.request({"op": "stats"}, timeout=60)
            pids = {r["replica"]: r["pid"] for r in stats0["replicas"]}
            t_kill = [None]

            def fire(event):
                kind, at = event["kind"], event["at"]
                if kind == "replica_kill":
                    print(f"chaos: SIGKILL replica {at} (pid {pids[at]})",
                          flush=True)
                    t_kill[0] = time.monotonic()
                    os.kill(pids[at], signal.SIGKILL)
                elif kind == "replica_slow":
                    ms = event.get("until", 100)
                    print(f"chaos: slow replica {at} by {ms}ms/flush", flush=True)
                    client.send(
                        {"id": f"slow-{at}", "op": "slow", "replica": at,
                         "ms": ms, "flushes": 40}
                    )
                elif kind == "reload_corrupt":
                    # A torn NEW publish: different save_id, readable
                    # signature, corrupt array data — the watcher fans a
                    # reload that must FAIL cleanly on every replica
                    # while serving continues on the loaded state.
                    print("chaos: publishing a torn successor checkpoint",
                          flush=True)
                    # analysis: ok atomic-publish deliberate corruption injection — tearing the publish IS the fault under test
                    with open(model_file, "wb") as f:
                        f.write(corrupt_bytes)

            # Spread the schedule across the stream's middle half.
            step = max(1, SERVE_REQUESTS // (2 * (len(serving) + 1)))
            events = {
                SERVE_REQUESTS // 4 + k * step: (lambda e=e: fire(e))
                for k, e in enumerate(serving)
            }
            _drive_frames(fc, parsed, base=10_000, qps=SERVE_QPS, events=events)
            ids = [10_000 + i for i in range(len(lines))]
            missing = fc.wait_answered(ids, timeout=120)
            result["unanswered"] = len(missing)
            result["client_failovers"] = fc.failovers

            # Heal the corrupt checkpoint: the watcher must pick the good
            # bytes back up (same content ⇒ same scores) — reload
            # failures were counted while it was torn.
            if any(e["kind"] == "reload_corrupt" for e in serving):
                # analysis: ok atomic-publish healing the injected corruption in place — same deliberate-fault channel as the tear
                with open(model_file, "wb") as f:
                    f.write(good_bytes)

            with fc.lock:
                answered = dict(fc.results)
            scored = mismatched = typed = 0
            codes: dict[str, int] = {}
            for i in range(len(lines)):
                r = answered.get(10_000 + i)
                if r is None:
                    continue
                status, score = r
                if status == "ok":
                    scored += 1
                    if score != baseline.get(i):
                        mismatched += 1
                else:
                    typed += 1
                    codes[status] = codes.get(status, 0) + 1
            result.update(
                scored=scored,
                typed_errors=typed,
                typed_codes=codes,
                scores_mismatched=mismatched,
            )

            # Recovery: all replicas healthy again, MTTR on the books.
            deadline = time.monotonic() + 120
            snap = None
            while time.monotonic() < deadline:
                snap = client.request({"op": "ping"}, timeout=30)
                if all(r["state"] == "healthy" for r in snap["replicas"]):
                    break
                time.sleep(0.5)
            stats = client.request({"op": "stats"}, timeout=60)
            result["replica_restarts"] = sum(
                r["restarts"] for r in stats["replicas"]
            )
            result["mttr_s"] = stats.get("mttr_s", [])
            result["mttr_s_detection_to_healthy"] = (
                stats["mttr_s"][0] if stats.get("mttr_s") else None
            )
            if t_kill[0] is not None and stats.get("mttr_s"):
                # Kill → healthy as the CLIENT would measure it (includes
                # the router's detection latency, not just its restart).
                result["kill_observed"] = True
            steady = {}
            reload_failures = {}
            delta_or_reloads = {}
            for idx, eng in stats.get("engines", {}).items():
                steady[idx] = eng.get("steady_compiles")
                e = eng.get("engine", {})
                reload_failures[idx] = e.get("reload_failures")
                delta_or_reloads[idx] = (e.get("reloads"), e.get("delta_reloads"))
            result["steady_compiles_by_replica"] = steady
            result["reload_failures_by_replica"] = reload_failures
            result["reloads_by_replica"] = delta_or_reloads
            result["all_healthy_after"] = bool(
                snap and all(r["state"] == "healthy" for r in snap["replicas"])
            )
            fc.close()
            client.close()
        except Exception as e:  # the probe must always write its verdict
            hard_fail = repr(e)
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    ok = (
        hard_fail is None
        and result.get("baseline_unscored") == 0
        and result.get("unanswered") == 0
        and result.get("scores_mismatched") == 0
        and result.get("replica_restarts", 0) >= 1
        and result.get("all_healthy_after")
        and all(
            v == 0 for v in result.get("steady_compiles_by_replica", {}).values()
        )
    )
    if any(e["kind"] == "reload_corrupt" for e in serving):
        # The torn successor must have been ATTEMPTED and survived — a
        # probe where no replica even tried the reload tested nothing.
        ok = ok and any(
            (v or 0) >= 1
            for v in result.get("reload_failures_by_replica", {}).values()
        )
    if hard_fail:
        result["error"] = hard_fail
    result["ok"] = ok
    write_json_artifact(out_path, result)
    print(f"chaos: wrote {out_path} (ok={ok})")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trials", type=int, default=3, metavar="N",
                    help="chaos trials per path (seeded kill steps)")
    ap.add_argument("--seed", type=int, default=1106)
    ap.add_argument("--sharded", action="store_true",
                    help="also run the dist_train (8-device CPU mesh) path")
    ap.add_argument("--processes", type=int, default=1, metavar="N",
                    help="N > 1: chaos the REAL multi-process pod instead "
                    "(dist_train under the pod supervisor, gloo CPU; each "
                    "trial SIGKILLs one host — victims alternate between "
                    "the writer and a survivor)")
    ap.add_argument("--serve", action="store_true",
                    help="chaos the SERVING tier: a live 2-replica socket "
                    "front end under replica kill/slow/corrupt faults "
                    "(writes PROBE_SERVE_CHAOS_r16.json)")
    ap.add_argument("--serve-plan",
                    default="replica_kill@0,replica_slow@1:150,reload_corrupt@0",
                    metavar="SPEC",
                    help="FaultPlan spec for --serve (serving kinds only; "
                    "@N is the replica index)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.serve:
        return _serve_chaos(args)
    pod = args.processes > 1
    out_path = args.out or os.path.join(
        REPO, "PROBE_MTTR_DIST_r07.json" if pod else "PROBE_MTTR_r06.json"
    )

    rng = random.Random(args.seed)
    modes = (
        ["dist_train"]
        if pod
        else ["train"] + (["dist_train"] if args.sharded else [])
    )
    from fast_tffm_tpu.telemetry import artifact_stamp, write_json_artifact

    result: dict = {
        # Envelope identity keys: the chaos trials' JSONL lives (and dies)
        # in per-trial tempdirs, so this stamp names the probe invocation;
        # the serve probe's tier ADOPTS its run_id (see _serve_chaos).
        **artifact_stamp(),
        "steps_total": STEPS,
        "delta_every_steps": DELTA_EVERY,
        "seed": args.seed,
        "paths": {},
    }
    if pod:
        result["processes"] = args.processes
    ok = True
    for mode in modes:
        with tempfile.TemporaryDirectory(prefix="chaos-base-") as d:
            _write_dataset(os.path.join(d, "t.libsvm"))
            t0 = time.monotonic()
            proc = _run(
                mode, _write_cfg(d, args.processes),
                *(["--supervised"] if pod else []),
                processes=args.processes,
            )
            if proc.returncode != 0:
                print(proc.stdout[-2000:], file=sys.stderr)
                print(f"chaos: {mode} baseline failed rc={proc.returncode}",
                      file=sys.stderr)
                return 1
            base_wall = time.monotonic() - t0
            base_losses = _losses(os.path.join(d, "run.jsonl"))
        assert len(base_losses) == STEPS, (
            f"baseline logged {len(base_losses)} steps, wanted {STEPS}"
        )
        trials = []
        for i in range(max(1, args.trials)):
            kill_at = rng.randrange(4, STEPS - 3)
            victim = i % args.processes if pod else 0
            label = f" victim=host{victim}" if pod else ""
            print(f"chaos: {mode} kill@{kill_at}{label} ...", flush=True)
            trials.append(
                _trial(mode, kill_at, base_losses,
                       processes=args.processes, victim=victim)
            )
        mttrs = [
            m for t in trials for m in t.get("mttr_s", [])
            if isinstance(m, (int, float))
        ]
        path_ok = all(
            t.get("supervisor_rc") == 0 and t.get("losses_bit_identical")
            for t in trials
        )
        ok = ok and path_ok
        result["paths"][mode] = {
            "baseline_wall_s": round(base_wall, 3),
            "trials": trials,
            "mttr_s_median": round(statistics.median(mttrs), 3) if mttrs else None,
            "mttr_s_max": round(max(mttrs), 3) if mttrs else None,
            "all_losses_bit_identical": path_ok,
        }
    write_json_artifact(out_path, result)
    print(f"chaos: wrote {out_path} (ok={ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
