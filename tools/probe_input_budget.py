#!/usr/bin/env python
"""Input→chip budget: per-stage rates at the headline batch (VERDICT r4 #5).

Separates the end-to-end streamed path into its stages, each measured in
isolation at the headline shape (B=65536, nnz=39, vocab 2^24, FMB input):

  fmb_read_rows_s    memmap FMB → numpy batch arrays (host only)
  h2d_bytes_s        device_put of one pre-read batch, value-synced
  step_rate          the device-only train step (same shapes)
  e2e_rate           stream → H2D → step with prefetch (the real path)

Plus the 2-PROCESS input-scaling artifact: the same sharded-input
global-batch assembly dist_train uses (block-cyclic line shards →
make_global_batch) driven by 1 vs 2 real OS processes over a localhost
jax.distributed CPU mesh, NO train step — the measured quantity is
parse+assembly throughput, which must scale with processes.

Writes PROBE_INPUT_r05.json.  Usage:
  python tools/probe_input_budget.py [--skip-tpu] [--rows 400000]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 65536
NNZ = 39
VOCAB = 1 << 24


def tpu_stages(res: dict, rows: int) -> None:
    import jax
    import numpy as np

    import bench
    from fast_tffm_tpu.data.binary import fmb_batch_stream
    from fast_tffm_tpu.models import Batch, FMModel
    from fast_tffm_tpu.trainer import init_packed_state, make_packed_train_step

    path = bench.ensure_scale_fmb(VOCAB, rows=rows)

    def read_all():
        n = 0
        for parsed, w in fmb_batch_stream(
            [path], batch_size=BATCH, vocabulary_size=VOCAB,
            hash_feature_id=True, max_nnz=NNZ, epochs=1, drop_remainder=True,
        ):
            n += parsed.ids.shape[0]
        return n

    n = read_all()  # warm page cache
    t0 = time.perf_counter()
    n = read_all()
    res["fmb_read_rows_s"] = round(n / (time.perf_counter() - t0), 1)

    # One batch, H2D isolated (value-synced by fetching a corner element).
    parsed, w = next(iter(fmb_batch_stream(
        [path], batch_size=BATCH, vocabulary_size=VOCAB, hash_feature_id=True,
        max_nnz=NNZ, epochs=1, drop_remainder=True,
    )))
    host_arrays = [
        np.ascontiguousarray(parsed.ids.astype(np.int32)),
        np.ascontiguousarray(parsed.vals),
        np.ascontiguousarray(parsed.labels),
        np.ascontiguousarray(w),
    ]
    bytes_per_batch = sum(a.nbytes for a in host_arrays)
    res["h2d_bytes_per_batch"] = bytes_per_batch

    def h2d_once():
        devs = [jax.device_put(a) for a in host_arrays]
        for d in devs:
            np.asarray(d[..., :1] if d.ndim else d)  # force
        return devs

    h2d_once()
    times = []
    for _ in range(6):
        t0 = time.perf_counter()
        h2d_once()
        times.append(time.perf_counter() - t0)
    res["h2d_bytes_s"] = round(bytes_per_batch / min(times), 1)
    res["h2d_batch_ms_best"] = round(min(times) * 1e3, 2)
    res["h2d_batch_ms_median"] = round(sorted(times)[len(times) // 2] * 1e3, 2)

    # Device-only step rate at the same shapes (the bench headline regime).
    model = FMModel(vocabulary_size=VOCAB, factor_num=8, order=2)
    state = init_packed_state(model, jax.random.key(0), accumulator="row")
    step = make_packed_train_step(model, 0.01, "auto")
    rng = np.random.default_rng(0)
    batches = [
        bench.make_batch(bench.zipf_ids(rng, (BATCH, NNZ), VOCAB), i)
        for i in range(4)
    ]
    state, rate = bench.measure(step, state, batches, iters=10, batch_size=BATCH)
    res["step_rate"] = round(rate, 1)

    # End-to-end: stream → H2D → step, prefetch depth 8.
    from fast_tffm_tpu.utils.prefetch import prefetch

    def stream():
        raw = fmb_batch_stream(
            [path], batch_size=BATCH, vocabulary_size=VOCAB,
            hash_feature_id=True, max_nnz=NNZ, epochs=1, drop_remainder=True,
        )
        return prefetch(
            (Batch.from_parsed(p, w, with_fields=False) for p, w in raw), depth=8
        )

    count = 0
    for b in stream():  # warm
        state, _ = step(state, b)
        count += 1
    bench.forced_sync(state)
    t0 = time.perf_counter()
    for b in stream():
        state, _ = step(state, b)
    bench.forced_sync(state)
    dt = time.perf_counter() - t0
    res["e2e_rate"] = round(count * BATCH / dt, 1)


_WORKER = textwrap.dedent(
    """
    import sys, time, json
    pid, nproc, port, path, batch, nnz = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
        int(sys.argv[5]), int(sys.argv[6]))
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    if nproc > 1:
        jax.distributed.initialize(
            f"127.0.0.1:{{port}}", num_processes=nproc, process_id=pid)
    import numpy as np
    from fast_tffm_tpu.data.binary import fmb_batch_stream
    from fast_tffm_tpu.parallel import make_global_batch, make_mesh
    from fast_tffm_tpu.utils.prefetch import prefetch

    mesh = make_mesh(None, 1)  # [2*nproc, 1] global mesh
    local_bs = batch // nproc

    def stream():
        raw = fmb_batch_stream(
            [path], batch_size=local_bs, vocabulary_size={vocab},
            hash_feature_id=True, max_nnz=nnz, epochs=1,
            shard_index=pid, shard_count=nproc, shard_block=local_bs,
            drop_remainder=True,
        )
        return prefetch(
            ((make_global_batch(mesh, p, w, with_fields=False), p) for p, w in raw),
            depth=8,
        )

    n = 0
    for b, p in stream():  # warm (page cache, jit of stitching)
        n += 1
    t0 = time.perf_counter()
    m = 0
    for b, p in stream():
        # Force this process's shard of the assembled global array (a full
        # np.asarray would need non-addressable shards on nproc > 1).
        float(np.asarray(b.labels.addressable_shards[0].data)[0])
        m += 1
    dt = time.perf_counter() - t0
    print(json.dumps({{"pid": pid, "batches": m,
                       "rows_s": m * batch / dt / 1.0}}), flush=True)
    """
).format(repo=REPO, vocab=VOCAB)


def input_scaling(res: dict, rows: int) -> None:
    """1-process vs 2-process sharded parse+assembly (CPU mesh, no step)."""
    import bench

    path = bench.ensure_scale_fmb(VOCAB, rows=rows)
    out = {}
    for nproc in (1, 2):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER, str(pid), str(nproc), str(port),
                 path, str(BATCH), str(NNZ)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for pid in range(nproc)
        ]
        rates = []
        try:
            for p in procs:
                o, e = p.communicate(timeout=900)
                if p.returncode:
                    out[f"p{nproc}_error"] = (e or o).strip().splitlines()[-1][-300:]
                    break
                rates.append(json.loads(o.strip().splitlines()[-1])["rows_s"])
            else:
                # Each process iterates the SAME global batches; the global
                # assembly rate is the slowest participant's.
                out[f"p{nproc}_rows_s"] = round(min(rates), 1)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()  # a timed-out/odd-exit peer must not linger
                    p.wait(timeout=5)  # reap — no zombies holding the port
    if "p1_rows_s" in out and "p2_rows_s" in out:
        out["scaling_x"] = round(out["p2_rows_s"] / out["p1_rows_s"], 2)
    res["input_scaling"] = out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1 << 19)
    ap.add_argument("--skip-tpu", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "PROBE_INPUT_r05.json"))
    args = ap.parse_args(argv)

    res = {"batch": BATCH, "nnz": NNZ, "vocab": VOCAB, "fmb_rows": args.rows}
    if not args.skip_tpu:
        tpu_stages(res, args.rows)
        print("tpu stages ->", {k: v for k, v in res.items() if "rate" in k or "h2d" in k or "read" in k}, flush=True)
    input_scaling(res, args.rows)
    print("input scaling ->", res["input_scaling"], flush=True)
    from fast_tffm_tpu.telemetry import write_json_artifact

    write_json_artifact(args.out, res, sort_keys=False)
    print("wrote", args.out)
    return 0


if __name__ == "__main__":
    from fast_tffm_tpu.telemetry import arm_hang_exit

    arm_hang_exit(seconds=2700, what="probe_input_budget.py")
    raise SystemExit(main())
