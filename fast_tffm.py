#!/usr/bin/env python
"""Entry script with the reference's CLI shape:

    python fast_tffm.py {train,predict,dist_train,dist_predict} <cfg>

(see fast_tffm_tpu/cli.py; `renyi533/fast_tffm` :: fast_tffm.py).
"""

import sys

from fast_tffm_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
